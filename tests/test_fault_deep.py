"""Deep fault-injection tier (round-2): node death mid-resize with
abort + recovery, anti-entropy convergence from bidirectional replica
divergence under concurrent writes, and a server restart over a torn
WAL.  Parity: internal/clustertests/cluster_test.go:69-80 (pumba
container pauses), cluster.go:1250 (resize abort), AE §3.5."""

from __future__ import annotations

import threading

import pytest

from pilosa_tpu.api import API
from pilosa_tpu.parallel.cluster import Node, TransportError
from pilosa_tpu.parallel.membership import heartbeat_round
from pilosa_tpu.parallel.resize import ResizeError, Resizer
from pilosa_tpu.parallel.syncer import HolderSyncer
from pilosa_tpu.shardwidth import SHARD_WIDTH

from tests.test_cluster import make_cluster


def _seed(node, n_shards=6, row=1):
    cols = [s * SHARD_WIDTH + 11 * s for s in range(n_shards)]
    node.create_index("i")
    node.create_field("i", "f")
    API(node).import_bits("i", "f", [row] * len(cols), cols)
    return cols


class TestNodeDiesMidResize:
    def test_source_dies_mid_resize_aborts_then_recovers(self, tmp_path):
        from pilosa_tpu.models.holder import Holder
        from pilosa_tpu.parallel.cluster import Cluster
        from pilosa_tpu.parallel.node import ClusterNode

        transport, nodes = make_cluster(tmp_path, n=2, replica_n=2)
        cols = _seed(nodes[0])
        want = len(cols)

        joiner_holder = Holder(str(tmp_path / "node2"))
        joiner = ClusterNode(
            joiner_holder,
            Cluster("node2", nodes=[Node(id="node2")], replica_n=1,
                    transport=transport))

        # kill node1 the moment the first resize instruction is
        # dispatched: fragment fetches from it fail mid-job
        real_send = transport.send_message
        state = {"instructions": 0}

        def chaotic_send(node, message):
            if message.get("type") == "resize-instruction":
                state["instructions"] += 1
                transport.set_down("node1")
            return real_send(node, message)

        transport.send_message = chaotic_send
        try:
            with pytest.raises((ResizeError, TransportError)):
                Resizer(nodes[0]).run(add=Node(id="node2"))
        finally:
            transport.send_message = real_send

        # abort path: coordinator back to NORMAL, membership unchanged,
        # reads exact from the surviving replica set
        assert nodes[0].cluster.state == "NORMAL"
        assert len(nodes[0].cluster.sorted_nodes()) == 2
        assert nodes[0].executor.execute("i", "Count(Row(f=1))")[0] == want
        # writes unblocked after abort (node1 still dark: best-effort)
        API(nodes[0]).import_bits("i", "f", [1], [3 * SHARD_WIDTH + 999])
        want += 1

        # node1 comes back; AE repairs the write it missed, then the
        # retried resize completes and every node (including the
        # joiner) answers the full result
        transport.set_down("node1", False)
        HolderSyncer(nodes[0]).sync_holder()
        HolderSyncer(nodes[1]).sync_holder()
        summary = Resizer(nodes[0]).run(add=Node(id="node2"))
        assert summary["transfers"] > 0
        for nd in (*nodes, joiner):
            assert nd.executor.execute("i", "Count(Row(f=1))")[0] == want

    def test_resize_abort_flag_mid_job(self, tmp_path):
        """Explicit abort (api.go:1250): the flag set between
        instructions stops the job and restores NORMAL."""
        from pilosa_tpu.models.holder import Holder
        from pilosa_tpu.parallel.cluster import Cluster
        from pilosa_tpu.parallel.node import ClusterNode

        transport, nodes = make_cluster(tmp_path, n=2, replica_n=1)
        _seed(nodes[0])
        Holder(str(tmp_path / "node2"))  # dir exists for the joiner
        r = Resizer(nodes[0])

        real_send = transport.send_message

        def abort_after_first(node, message):
            resp = real_send(node, message)
            if message.get("type") == "resize-instruction":
                r.abort()
            return resp

        transport.send_message = abort_after_first
        try:
            # abort only raises if a later instruction existed; either
            # way the job must leave the cluster NORMAL and writable
            try:
                r.run(add=Node(id="node2"))
            except ResizeError:
                pass
        finally:
            transport.send_message = real_send
        assert nodes[0].cluster.state == "NORMAL"
        API(nodes[0]).import_bits("i", "f", [1], [42])


class TestBidirectionalDivergence:
    def test_ae_converges_both_directions_under_concurrent_writes(
            self, tmp_path):
        """Replica set {node0, node1} diverges BOTH ways (each holds
        bits the other missed), a writer keeps importing during repair,
        and anti-entropy still converges every replica to the union."""
        transport, nodes = make_cluster(tmp_path, n=2, replica_n=2)
        n0, n1 = nodes
        n0.create_index("i")
        n0.create_field("i", "f")
        api0, api1 = API(n0), API(n1)

        base = [s * SHARD_WIDTH + s for s in range(4)]
        api0.import_bits("i", "f", [1] * len(base), base)

        # direction 1: node1 dark, bits land only on node0
        transport.set_down("node1")
        only0 = [s * SHARD_WIDTH + 1000 + s for s in range(4)]
        api0.import_bits("i", "f", [1] * len(only0), only0)
        transport.set_down("node1", False)

        # direction 2: node0 dark, bits land only on node1
        transport.set_down("node0")
        only1 = [s * SHARD_WIDTH + 2000 + s for s in range(4)]
        api1.import_bits("i", "f", [1] * len(only1), only1)
        transport.set_down("node0", False)

        # concurrent writer hammers a second row while AE repairs row 1
        stop = threading.Event()
        written: list[int] = []

        def writer():
            i = 0
            while not stop.is_set() and i < 200:
                col = (i % 4) * SHARD_WIDTH + 5000 + i
                api0.import_bits("i", "f", [2], [col])
                written.append(col)
                i += 1

        t = threading.Thread(target=writer)
        t.start()
        try:
            for _ in range(3):  # repeated passes, as the AE loop would
                HolderSyncer(n0).sync_holder()
                HolderSyncer(n1).sync_holder()
        finally:
            stop.set()
            t.join()
        # one final quiesced pass picks up anything written mid-repair
        HolderSyncer(n0).sync_holder()
        HolderSyncer(n1).sync_holder()

        want1 = sorted(base + only0 + only1)
        want2 = sorted(set(written))
        for nd in nodes:
            row1 = nd.executor.execute("i", "Row(f=1)")[0]
            assert sorted(int(c) for c in row1.columns()) == want1, nd
            row2 = nd.executor.execute("i", "Row(f=2)")[0]
            assert sorted(int(c) for c in row2.columns()) == want2, nd
        # per-node LOCAL fragments agree too (not just fan-out results):
        # both replicas of every shard hold the union
        for nd in nodes:
            f = nd.holder.index("i").field("f")
            for s in range(4):
                frag = f.view("standard").fragment(s)
                assert frag is not None
                import numpy as np

                row_words = frag.row(1)
                bits = (np.flatnonzero(np.unpackbits(
                    row_words.view(np.uint8), bitorder="little"))
                    if row_words is not None else [])
                local = sorted(s * SHARD_WIDTH + int(p) for p in bits)
                assert local == [c for c in want1
                                 if c // SHARD_WIDTH == s], (nd, s)


class TestRestartOverTornWal:
    def test_server_restarts_over_truncated_wal(self, tmp_path):
        """SIGKILL-style stop, torn WAL tail, restart: the server must
        boot and serve every complete record (fragment-level torn-tail
        test, lifted to the full server lifecycle)."""
        import glob
        import os

        from pilosa_tpu.server.server import Server

        d = str(tmp_path / "n0")
        s = Server(data_dir=d, coordinator=True)
        s.open()
        from pilosa_tpu.server.client import InternalClient

        c = InternalClient(timeout=30)
        c.post_json(s.uri + "/index/i", {})
        c.post_json(s.uri + "/index/i/field/f", {})
        # 20 separate batches -> 20 bulk WAL records; tearing the file
        # tail can only lose the LAST record (batch of 10)
        for b in range(20):
            cols = list(range(b * 10, b * 10 + 10))
            c.post_json(s.uri + "/index/i/field/f/import",
                        {"rowIDs": [1] * 10, "columnIDs": cols})
        # simulate SIGKILL: release only the dir lock + sockets, no
        # holder close, no WAL flush beyond what writes already did
        s._stop.set()
        s.handler.close()
        s._client.close()
        s.holder._release_dir_lock()
        c.close()

        wals = [p for p in glob.glob(d + "/**/*.wal", recursive=True)
                if os.path.getsize(p) > 0 and "/f/" in p]  # field f's WAL,
                # not the auto-created _exists field's (glob order varies)
        assert wals, "expected a live field WAL after an unclean stop"
        torn = wals[0]
        os.truncate(torn, os.path.getsize(torn) - 3)

        s2 = Server(data_dir=d, coordinator=True)
        s2.open()
        c2 = InternalClient(timeout=30)
        r = c2.post_json(s2.uri + "/index/i/query",
                         {"query": "Count(Row(f=1))"})
        got = r["results"][0]
        # the torn last bulk record loses exactly its batch of 10;
        # every complete record replays
        assert got == 190, got
        c2.close()
        s2.close()


class TestPairPartition:
    """Bidirectional pair partition (the pumba netem scenario,
    internal/clustertests/cluster_test.go:69-80): two LIVE nodes stop
    hearing each other while both keep serving everyone else.  Reads
    from either side must fail over to the reachable replica, SWIM
    must NOT declare either side dead (indirect ping-req through the
    third node vouches for both), and anti-entropy passes racing the
    partition must skip the unreachable peer without corrupting."""

    def test_partition_failover_vouching_and_ae_race(self, tmp_path):
        import random

        transport, nodes = make_cluster(tmp_path, n=3, replica_n=2)
        cols = _seed(nodes[0])
        want = len(cols)
        for nd in nodes:
            assert nd.executor.execute("i", "Count(Row(f=1))")[0] == want

        transport.set_partition("node0", "node1")
        try:
            # direct link is dead both ways
            n0, n1 = nodes[0], nodes[1]
            with pytest.raises(TransportError):
                n0.cluster.transport.send_message(
                    Node(id="node1"), {"type": "ping"})
            with pytest.raises(TransportError):
                n1.cluster.transport.send_message(
                    Node(id="node0"), {"type": "ping"})
            # ...but a third party still reaches both sides
            assert transport.send_message(
                Node(id="node0"), {"type": "ping"}).get("ok")

            # reads stay exact from EVERY node: shards whose primary
            # sits across the cut fail over to the reachable replica
            for nd in nodes:
                assert nd.executor.execute(
                    "i", "Count(Row(f=1))")[0] == want

            # SWIM: node0's round probes node1 directly (fails) then
            # escalates to ping-req via node2 (succeeds) -> no state
            # change, nobody marked DOWN
            changes = heartbeat_round(nodes[0], k=2,
                                      rng=random.Random(7))
            assert not changes, changes
            assert all(p.state != "DOWN"
                       for p in nodes[0].cluster.sorted_nodes())

            # anti-entropy racing the partition: each syncer skips the
            # peer it cannot reach; nothing is lost or half-applied
            for nd in nodes:
                HolderSyncer(nd).sync_holder()
            for nd in nodes:
                assert nd.executor.execute(
                    "i", "Count(Row(f=1))")[0] == want

            # writes land on the reachable replica set; the cut replica
            # is healed by AE after the partition lifts
            API(nodes[2]).import_bits("i", "f", [1],
                                      [5 * SHARD_WIDTH + 123])
            want += 1
        finally:
            transport.set_partition("node0", "node1", False)

        for nd in nodes:
            HolderSyncer(nd).sync_holder()
        for nd in nodes:
            assert nd.executor.execute("i", "Count(Row(f=1))")[0] == want


class TestStaleViewImport:
    """Write-side counterpart of the round-5 read-vs-cleanup race: a
    replica delivery for a shard the receiver does not own (per its
    CURRENT view) is refused (reference api.go
    ErrClusterDoesNotOwnShard), and the origin's fan-out re-resolves
    the owner set and retries — a stale-view write must never land
    its only copy on an ex-owner whose fragments the post-resize
    sweep deletes."""

    def test_non_owner_delivery_refused(self, tmp_path):
        transport, nodes = make_cluster(tmp_path, n=3, replica_n=1)
        nodes[0].create_index("i")
        nodes[0].create_field("i", "f")
        shard = 0
        owner = nodes[0].cluster.shard_nodes("i", shard)[0].id
        non_owner = next(nd for nd in nodes
                         if nd.cluster.local_id != owner)
        col = shard * SHARD_WIDTH + 5
        resp = non_owner.receive_message(
            {"type": "import", "index": "i", "field": "f",
             "rows": [1], "cols": [col], "timestamps": None,
             "clear": False})
        assert resp.get("unowned") and not resp.get("ok"), resp
        # nothing was absorbed locally
        view = non_owner.holder.index("i").field("f").view("standard")
        assert view is None or view.fragment(shard) is None

    def test_stale_origin_reroutes_after_refusal(self, tmp_path,
                                                 monkeypatch):
        transport, nodes = make_cluster(tmp_path, n=3, replica_n=1)
        nodes[0].create_index("i")
        nodes[0].create_field("i", "f")
        shard = 0
        owner = nodes[0].cluster.shard_nodes("i", shard)[0].id
        wrong = next(n for n in nodes[0].cluster.sorted_nodes()
                     if n.id != owner and n.id != "node0")
        real = nodes[0].cluster.shard_nodes
        calls = {"n": 0}

        def stale(index, s):
            calls["n"] += 1
            if calls["n"] == 1:
                return [wrong]  # stale view: delivers to an ex-owner
            return real(index, s)

        monkeypatch.setattr(nodes[0].cluster, "shard_nodes", stale)
        col = shard * SHARD_WIDTH + 7
        API(nodes[0]).import_bits("i", "f", [1], [col])
        assert calls["n"] >= 2, "fan-out never re-resolved owners"
        # the bit landed on the TRUE owner; exact from every node
        for nd in nodes:
            assert int(nd.executor.execute(
                "i", "Count(Row(f=1))")[0]) == 1, nd.cluster.local_id

    def test_stale_origin_set_reroutes_after_refusal(self, tmp_path,
                                                     monkeypatch):
        """Same contract on the PQL write path: a remote Set delivered
        to a non-owner raises UnownedShardError; the origin's
        replication loop re-resolves the owner set and retries."""
        transport, nodes = make_cluster(tmp_path, n=3, replica_n=1)
        nodes[0].create_index("i")
        nodes[0].create_field("i", "f")
        shard = 0
        owner = nodes[0].cluster.shard_nodes("i", shard)[0].id
        wrong = next(n for n in nodes[0].cluster.sorted_nodes()
                     if n.id != owner and n.id != "node0")
        real = nodes[0].cluster.shard_nodes
        calls = {"n": 0}

        def stale(index, s):
            calls["n"] += 1
            if calls["n"] == 1:
                return [wrong]
            return real(index, s)

        monkeypatch.setattr(nodes[0].cluster, "shard_nodes", stale)
        col = shard * SHARD_WIDTH + 9
        assert nodes[0].executor.execute("i", f"Set({col}, f=2)") == [True]
        assert calls["n"] >= 2, "replication never re-resolved owners"
        for nd in nodes:
            assert int(nd.executor.execute(
                "i", "Count(Row(f=2))")[0]) == 1, nd.cluster.local_id

    def test_cleanup_rescues_stranded_bits_before_delete(self, tmp_path):
        """A write whose origin's OWN stale view listed an ex-owner as
        owner has no peer that can refuse it — the bits strand there.
        The unowned sweep must push them to the current owners (AE
        diff) and verify coverage by block checksum BEFORE deleting,
        never discarding the only copy."""
        transport, nodes = make_cluster(tmp_path, n=3, replica_n=1)
        nodes[0].create_index("i")
        nodes[0].create_field("i", "f")
        shard = 0
        owner_id = nodes[0].cluster.shard_nodes("i", shard)[0].id
        owner = next(nd for nd in nodes
                     if nd.cluster.local_id == owner_id)
        stray = next(nd for nd in nodes
                     if nd.cluster.local_id != owner_id)
        col = shard * SHARD_WIDTH + 11
        # strand the only copy on the non-owner
        stray.holder.index("i").field("f").import_bits([3], [col])
        stray.cleanup_unowned()
        # fragment removed locally...
        view = stray.holder.index("i").field("f").view("standard")
        assert view is None or view.fragment(shard) is None
        # ...and the bits now live on the true owner
        ofrag = owner.holder.index("i").field("f") \
            .view("standard").fragment(shard)
        assert ofrag is not None
        import numpy as np

        arr = ofrag._rows.get(3)
        off = col - shard * SHARD_WIDTH
        assert arr is not None and (arr[off // 32] >> (off % 32)) & 1, \
            "stranded bit was not rescued to the owner"

    def test_refusal_contract_matches_http_client_error(self):
        """Over the production HTTP fabric a refusal arrives as
        ClientError (handler maps ExecutionError to 400), NOT
        TransportError — the origin's retry matcher must recognize the
        string contract on ANY exception type."""
        from pilosa_tpu.parallel.cluster import (
            UNOWNED_MARKER, refusal_is_unowned)
        from pilosa_tpu.parallel.executor import UnownedShardError
        from pilosa_tpu.server.client import ClientError

        assert refusal_is_unowned(UnownedShardError(7))
        assert refusal_is_unowned(
            ClientError(400, f"{UNOWNED_MARKER}: node does not own "
                             f"shard 7"))
        # unrelated errors that merely TALK about shard ownership must
        # not be mistaken for the refusal contract (it would convert
        # them into a silent 10 s convergence-retry loop)
        assert not refusal_is_unowned(
            ClientError(400, "node does not own shard 7"))
        assert not refusal_is_unowned(ClientError(400, "bad query"))
        assert not refusal_is_unowned(TransportError("connection refused"))


class TestGrayFailure:
    """Slow-but-alive node (gray failure): no TransportError fires, so
    nothing fails over — correctness must come from the write path
    actually WAITING for the slow replica, and SWIM must keep the
    node a member (it answers probes, late)."""

    def test_slow_node_stays_member_reads_and_writes_exact(
            self, tmp_path):
        import random

        transport, nodes = make_cluster(tmp_path, n=3, replica_n=2)
        cols = _seed(nodes[0])
        want = len(cols)
        transport.set_slow("node1", 0.05)
        try:
            # SWIM: probes are slow, not dead — no state change
            changes = heartbeat_round(nodes[0], k=2,
                                      rng=random.Random(3))
            assert not changes, changes
            # reads exact from every node (including through the slow
            # replica's owned shards)
            for nd in nodes:
                assert nd.executor.execute(
                    "i", "Count(Row(f=1))")[0] == want
            # writes replicate through the slow node synchronously —
            # target a shard the SLOW node owns, chosen dynamically so
            # a placement/width change can never silently skip the
            # replication assertion below
            slow_shard = next(
                s for s in range(6)
                if "node1" in [n.id
                               for n in nodes[0].cluster.shard_nodes(
                                   "i", s)])
            API(nodes[0]).import_bits(
                "i", "f", [1], [slow_shard * SHARD_WIDTH + 777])
            want += 1
            assert nodes[2].executor.execute(
                "i", "Set(99, f=1)")[0] is True
            want += 1
        finally:
            transport.set_slow("node1", 0.0)
        # the slow replica's LOCAL fragment carries the write — it was
        # not skipped while the node was slow
        frag = nodes[1].holder.index("i").field("f") \
            .view("standard").fragment(slow_shard)
        assert frag is not None, "slow replica never got the fragment"
        arr = frag._rows.get(1)
        off = 777
        assert arr is not None and (arr[off // 32] >> (off % 32)) & 1, \
            "write was skipped on the slow replica"
        for nd in nodes:
            assert nd.executor.execute(
                "i", "Count(Row(f=1))")[0] == want
