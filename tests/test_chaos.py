"""Chaos-ready serving (the failure-handling round): failpoint
registry semantics, per-peer circuit breakers (state machine +
fast-fail latency pin + heartbeat healing), hedged replica reads,
partial-result degradation (?partial=1) with exact missing-shard
accounting, the structured replica-exhaustion error, the device-OOM
evict-and-retry, and a 3-node chaos soak asserting every response is
a correct result, an explicit error, or a correctly-accounted
partial — never silently wrong data."""

from __future__ import annotations

import threading
import time

import pytest

from pilosa_tpu import faultinject as fi
from pilosa_tpu.api import API
from pilosa_tpu.parallel.cluster import (
    BREAKER_CLOSED,
    BREAKER_HALF_OPEN,
    BREAKER_OPEN,
    CircuitBreaker,
    TransportError,
)
from pilosa_tpu.parallel.executor import (
    ExecOptions,
    ExecutionError,
    ShardsUnavailableError,
)
from pilosa_tpu.parallel.membership import heartbeat_round
from pilosa_tpu.shardwidth import SHARD_WIDTH

from tests.test_cluster import make_cluster


@pytest.fixture(autouse=True)
def _disarm():
    fi.disarm()
    yield
    fi.disarm()


# ------------------------------------------------------------ failpoints


class TestFailpoints:
    def test_spec_parses_and_validates(self):
        fi.arm("client.request.send=error(transport)*3;"
               "executor.map_shard=delay(5)@2")
        snap = fi.snapshot()
        assert snap["armed"]
        assert set(snap["points"]) == {"client.request.send",
                                       "executor.map_shard"}
        fi.disarm("client.request.send")
        assert set(fi.snapshot()["points"]) == {"executor.map_shard"}
        fi.disarm()
        assert not fi.snapshot()["armed"]
        assert fi.armed is False

    def test_unknown_name_and_bad_action_rejected(self):
        with pytest.raises(ValueError, match="unknown failpoint"):
            fi.arm("no.such.site=error")
        with pytest.raises(ValueError, match="unparsable action"):
            fi.arm("device.dispatch=explode")
        with pytest.raises(ValueError, match="unknown error class"):
            fi.arm("device.dispatch=error(nuke)")
        # all-or-nothing: nothing armed by the failures above
        assert not fi.snapshot()["armed"]

    def test_error_count_and_nth_triggers(self):
        fi.arm("device.dispatch=error*2")
        with pytest.raises(fi.FailpointError):
            fi.hit("device.dispatch")
        with pytest.raises(fi.FailpointError):
            fi.hit("device.dispatch")
        fi.hit("device.dispatch")  # *2 exhausted: passes through
        p = fi.snapshot()["points"]["device.dispatch"]
        assert p["calls"] == 3 and p["triggers"] == 2 and p["exhausted"]

        fi.arm("device.dispatch=error@2")  # 1st, 3rd, 5th... calls
        with pytest.raises(fi.FailpointError):
            fi.hit("device.dispatch")
        fi.hit("device.dispatch")
        with pytest.raises(fi.FailpointError):
            fi.hit("device.dispatch")

    def test_error_classes(self):
        fi.arm("device.dispatch=error(transport)")
        with pytest.raises(TransportError):
            fi.hit("device.dispatch")
        fi.arm("device.dispatch=error(oom)")
        with pytest.raises(Exception, match="RESOURCE_EXHAUSTED"):
            fi.hit("device.dispatch")

    def test_delay_action(self):
        fi.arm("device.dispatch=delay(30)")
        t0 = time.perf_counter()
        fi.hit("device.dispatch")
        assert time.perf_counter() - t0 >= 0.025

    def test_disarmed_gate_is_module_bool(self):
        """The zero-overhead contract: sites gate on ``fi.armed``
        before calling hit(), so the disarmed hot path pays one
        attribute read (bench.py extras.faultinject pins the cost)."""
        assert fi.armed is False
        fi.arm("device.dispatch=error")
        assert fi.armed is True
        fi.disarm()
        assert fi.armed is False


# ------------------------------------------------------- circuit breaker


class TestCircuitBreaker:
    def test_state_machine(self):
        now = [0.0]
        b = CircuitBreaker(threshold=3, cooldown_s=5.0,
                           clock=lambda: now[0])
        assert b.state == BREAKER_CLOSED and b.allow()
        b.note_failure()
        b.note_failure()
        assert b.state == BREAKER_CLOSED  # below threshold
        b.note_failure()
        assert b.state == BREAKER_OPEN
        assert not b.allow() and not b.allow()
        assert b.snapshot()["fastFails"] == 2
        # cooldown elapses: exactly ONE half-open trial admitted
        now[0] = 5.0
        assert b.allow()
        assert b.state == BREAKER_HALF_OPEN
        assert not b.allow()  # concurrent call during the trial
        b.note_success()
        assert b.state == BREAKER_CLOSED
        assert b.snapshot()["opened"] == 1
        assert b.snapshot()["closed"] == 1

    def test_half_open_failure_reopens(self):
        now = [0.0]
        b = CircuitBreaker(threshold=1, cooldown_s=2.0,
                           clock=lambda: now[0])
        b.note_failure()
        assert b.state == BREAKER_OPEN
        now[0] = 2.0
        assert b.allow()  # the trial
        b.note_failure()
        assert b.state == BREAKER_OPEN
        assert not b.allow()  # cooling down again from t=2
        now[0] = 4.0
        assert b.allow()
        b.note_success()
        assert b.state == BREAKER_CLOSED

    def test_lost_half_open_trial_does_not_wedge(self):
        """A HALF_OPEN trial whose outcome never arrives (abandoned
        flight, crashed caller) must not blacklist the peer forever:
        after one more cooldown the breaker admits a fresh trial."""
        now = [0.0]
        b = CircuitBreaker(threshold=1, cooldown_s=1.0,
                           clock=lambda: now[0])
        b.note_failure()
        now[0] = 1.0
        assert b.allow()          # the trial — and it is never noted
        assert not b.allow()      # still outstanding
        now[0] = 2.0
        assert b.allow()          # timeout escape: a fresh trial
        b.note_success()
        assert b.state == BREAKER_CLOSED

    def test_success_resets_consecutive_count(self):
        b = CircuitBreaker(threshold=3)
        b.note_failure()
        b.note_failure()
        b.note_success()
        b.note_failure()
        b.note_failure()
        assert b.state == BREAKER_CLOSED  # never 3 consecutive

    def test_shed_never_opens_breaker(self, tmp_path):
        """A shed (429/503 from a live peer) is proof of life: the
        executor feeds it to note_peer_success, never note_failure."""
        transport, nodes = make_cluster(tmp_path, n=2, replica_n=1)
        c = nodes[0].cluster
        c.breaker_threshold = 1
        from pilosa_tpu.parallel.cluster import ShedByPeerError  # noqa: F401

        c.note_peer_success("node1")  # what the executor does on shed
        assert c.breaker("node1").state == BREAKER_CLOSED

    def test_heartbeat_probe_closes_open_breaker(self, tmp_path):
        """Half-open trials ride the membership heartbeat: a
        successful SWIM probe heals the breaker without query
        traffic."""
        transport, nodes = make_cluster(tmp_path, n=3, replica_n=2)
        c = nodes[0].cluster
        for _ in range(c.breaker_threshold):
            c.note_peer_failure("node2")
        b = c.breaker("node2")
        assert b.state == BREAKER_OPEN
        heartbeat_round(nodes[0])  # node2 is reachable: probe succeeds
        assert b.state == BREAKER_CLOSED


def _seed_rows(nodes, n_shards=6, row=1):
    """row bits spread over n_shards through node0; returns per-shard
    truth {shard: count}."""
    nodes[0].create_index("i")
    nodes[0].create_field("i", "f")
    truth = {}
    cols = []
    rows = []
    for s in range(n_shards):
        k = 2 + (s % 3)
        truth[s] = k
        for j in range(k):
            cols.append(s * SHARD_WIDTH + j)
            rows.append(row)
    API(nodes[0]).import_bits("i", "f", rows, cols)
    return truth


class TestBreakerFastFail:
    def test_breaker_open_queries_fast_fail_under_10pct_of_timeout(
            self, tmp_path):
        """The acceptance pin: a dead peer that costs a full RPC
        timeout per dial stalls the FIRST query; once its breaker is
        open, subsequent queries mapping to it fast-fail onto the next
        replica in < 10% of the configured timeout."""
        rpc_timeout = 0.5
        transport, nodes = make_cluster(tmp_path, n=3, replica_n=2)
        truth = _seed_rows(nodes)
        total = sum(truth.values())
        ex = nodes[0].executor
        assert ex.execute("i", "Count(Row(f=1))")[0] == total  # warm
        c = nodes[0].cluster
        # the victim must actually be a routing target of the query
        victim = next(k for k in c.shards_by_node("i", list(truth))
                      if k != c.local_id)
        # the warm query already created the breaker at the default
        # threshold; tighten the live instance so one failure opens it
        c.breaker(victim).threshold = 1
        real = transport.query_node

        def dead_slow(node, index, pql, shards, **kw):
            if node.id == victim:
                time.sleep(rpc_timeout)  # a sunk dial that times out
                raise TransportError(
                    f"node unreachable: {victim}: timed out")
            return real(node, index, pql, shards, **kw)

        transport.query_node = dead_slow
        try:
            # first query pays the timeout once, fails over, opens the
            # breaker (threshold 1) — and stays correct
            assert ex.execute("i", "Count(Row(f=1))")[0] == total
            assert c.breaker(victim).state == BREAKER_OPEN
            t0 = time.perf_counter()
            assert ex.execute("i", "Count(Row(f=1))")[0] == total
            elapsed = time.perf_counter() - t0
            assert elapsed < rpc_timeout * 0.1, (
                f"breaker-open query took {elapsed:.3f}s, "
                f"expected < {rpc_timeout * 0.1:.3f}s")
        finally:
            transport.query_node = real


# ---------------------------------------------------------- hedged reads


class TestHedgedReads:
    def _prime(self, tmp_path):
        transport, nodes = make_cluster(tmp_path, n=3, replica_n=2)
        truth = _seed_rows(nodes)
        ex = nodes[0].executor
        ex.hedge_min_samples = 2
        ex.hedge_min_s = 0.02
        ex.hedge_max_fraction = 1.0
        total = sum(truth.values())
        for _ in range(4):  # latency EWMA samples for both peers
            assert ex.execute("i", "Count(Row(f=1))")[0] == total
        return transport, nodes, ex, total

    def test_hedge_beats_slow_peer_and_stays_correct(self, tmp_path):
        transport, nodes, ex, total = self._prime(tmp_path)
        slow = 1.0
        transport.set_slow("node1", slow)
        transport.set_slow("node2", 0.0)
        t0 = time.perf_counter()
        got = ex.execute("i", "Count(Row(f=1))")[0]
        elapsed = time.perf_counter() - t0
        assert got == total
        # the hedge answered from the replica while the slow peer was
        # still sleeping — nowhere near the full delay
        assert elapsed < slow * 0.5, f"hedge did not engage: {elapsed:.3f}s"
        assert ex._hedge_issued >= 1
        assert ex._hedge_wins >= 1
        # the flight record carries the hedge evidence
        rec = ex.recorder.recent_records()[-1]
        assert rec.hedged >= 1 and rec.hedge_wins >= 1
        assert rec.to_dict()["hedged"] >= 1

    def test_hedge_bound_disables_hedging(self, tmp_path):
        transport, nodes, ex, total = self._prime(tmp_path)
        ex.hedge_max_fraction = 0.0  # hard off
        transport.set_slow("node1", 0.15)
        t0 = time.perf_counter()
        got = ex.execute("i", "Count(Row(f=1))")[0]
        elapsed = time.perf_counter() - t0
        assert got == total
        assert ex._hedge_issued == 0
        assert elapsed >= 0.14  # paid the slow peer in full

    def test_hedge_fraction_bound_holds(self, tmp_path):
        """hedges never exceed the configured fraction of RPC volume:
        with a tiny fraction and few RPCs, no hedge may issue."""
        transport, nodes, ex, total = self._prime(tmp_path)
        ex.hedge_max_fraction = 0.01  # needs 100+ RPCs per hedge
        rpcs_before = ex._hedge_rpcs
        transport.set_slow("node1", 0.1)
        assert ex.execute("i", "Count(Row(f=1))")[0] == total
        assert ex._hedge_issued <= ex.hedge_max_fraction * ex._hedge_rpcs
        assert ex._hedge_rpcs > rpcs_before


# ------------------------------------------------------- partial results


class TestPartialResults:
    def _outage(self, tmp_path):
        transport, nodes = make_cluster(tmp_path, n=3, replica_n=1)
        truth = _seed_rows(nodes)
        ex = nodes[0].executor
        total = sum(truth.values())
        assert ex.execute("i", "Count(Row(f=1))")[0] == total
        victim = "node2"
        victim_shards = sorted(
            s for s in truth
            if nodes[0].cluster.shard_nodes("i", s)[0].id == victim)
        assert victim_shards, "placement gave node2 no shards"
        transport.set_down(victim)
        return transport, nodes, ex, truth, total, victim, victim_shards

    def test_default_raises_structured_error(self, tmp_path):
        (transport, nodes, ex, truth, total, victim,
         victim_shards) = self._outage(tmp_path)
        with pytest.raises(ShardsUnavailableError,
                           match="replicas exhausted") as ei:
            ex.execute("i", "Count(Row(f=1))")
        e = ei.value
        assert e.shards == victim_shards
        assert all(e.causes[s] == {victim: "transport"}
                   for s in e.shards)
        assert isinstance(e, ExecutionError)  # back-compat hierarchy

    def test_partial_counts_and_missing_match_outage_exactly(
            self, tmp_path):
        (transport, nodes, ex, truth, total, victim,
         victim_shards) = self._outage(tmp_path)
        opt = ExecOptions(partial=True, missing=set())
        got = ex.execute("i", "Count(Row(f=1))", opt=opt)[0]
        assert sorted(opt.missing) == victim_shards
        assert got == total - sum(truth[s] for s in victim_shards)
        # Row() accounts the same way: reachable columns only
        opt2 = ExecOptions(partial=True, missing=set())
        row = ex.execute("i", "Row(f=1)", opt=opt2)[0]
        want = {s * SHARD_WIDTH + j for s in truth
                if s not in victim_shards for j in range(truth[s])}
        assert {int(c) for c in row.columns()} == want
        assert sorted(opt2.missing) == victim_shards

    def test_partial_results_never_enter_the_cache(self, tmp_path):
        """After a degraded partial read, healing the outage and
        re-running the same query (default semantics) must return the
        FULL truth — a partial value cached under the query's key
        would serve a hole forever."""
        from pilosa_tpu.runtime import resultcache

        resultcache.configure(enabled=True)
        (transport, nodes, ex, truth, total, victim,
         victim_shards) = self._outage(tmp_path)
        opt = ExecOptions(partial=True, missing=set())
        got = ex.execute("i", "Count(Row(f=1))", opt=opt)[0]
        assert got < total
        transport.set_down(victim, False)
        assert ex.execute("i", "Count(Row(f=1))")[0] == total
        # the gate itself: a request that accounted a missing shard
        # suppresses every fill it would perform
        assert ex._rc_fill_ok(opt) is False
        assert ex._rc_fill_ok(ExecOptions(partial=True,
                                          missing=set())) is True

    def test_default_path_unchanged_without_flag(self, tmp_path):
        """No-flag requests keep all-or-error semantics: partial
        machinery is inert (missing=None) and healthy results are
        identical."""
        transport, nodes = make_cluster(tmp_path, n=3, replica_n=1)
        truth = _seed_rows(nodes)
        ex = nodes[0].executor
        opt = ExecOptions()
        assert opt.partial is False and opt.missing is None
        assert ex.execute("i", "Count(Row(f=1))",
                          opt=opt)[0] == sum(truth.values())
        assert opt.missing is None  # never materialized


# ------------------------------------------------------- device OOM retry


class TestDeviceOomRetry:
    def test_fused_count_retries_once_after_evict(self, tmp_path):
        from pilosa_tpu import devobs
        from pilosa_tpu.runtime import residency

        transport, nodes = make_cluster(tmp_path, n=1)
        truth = _seed_rows(nodes, n_shards=4)
        ex = nodes[0].executor
        total = sum(truth.values())
        assert ex.execute("i", "Count(Row(f=1))")[0] == total  # warm
        obs = devobs.reset()
        ev0 = residency.manager().evictions
        fi.arm("device.dispatch=error(oom)*1")
        got = ex.execute("i", "Count(Row(f=1))", opt=ExecOptions(
            cache=False))[0]
        assert got == total
        assert obs.oom_retries == 1
        assert obs.snapshot()["oomRetries"] == 1
        assert residency.manager().evictions >= ev0

    def test_persistent_oom_still_errors(self, tmp_path):
        transport, nodes = make_cluster(tmp_path, n=1)
        _seed_rows(nodes, n_shards=4)
        ex = nodes[0].executor
        fi.arm("device.dispatch=error(oom)")  # every call
        with pytest.raises(Exception, match="RESOURCE_EXHAUSTED"):
            ex.execute("i", "Count(Row(f=1))",
                       opt=ExecOptions(cache=False))


# ------------------------------------------------------------ chaos soak


class TestChaosSoak:
    def test_three_node_soak_no_silent_wrong_data(self, tmp_path):
        """One of three nodes flaps, another carries injected latency,
        concurrent reads (default + partial) and writes flow — every
        read is a correct result, an explicit error, or a correctly-
        accounted partial, and read goodput stays >= 80%."""
        transport, nodes = make_cluster(tmp_path, n=3, replica_n=2)
        truth = _seed_rows(nodes)  # static row 1: the read target
        total = sum(truth.values())
        ex0 = nodes[0].executor
        assert ex0.execute("i", "Count(Row(f=1))")[0] == total
        transport.set_slow("node1", 0.05)  # 50 ms gray failure throughout

        stop = threading.Event()
        wrong: list[str] = []
        counts = {"ok": 0, "partial_ok": 0, "error": 0}
        lock = threading.Lock()

        def flapper():
            down = False
            while not stop.is_set():
                down = not down
                transport.set_down("node2", down)
                try:
                    heartbeat_round(nodes[0])
                except Exception:
                    pass
                stop.wait(0.15)
            transport.set_down("node2", False)

        def reader(use_partial: bool):
            node = nodes[0]
            while not stop.is_set():
                opt = ExecOptions(partial=use_partial,
                                  missing=set() if use_partial else None)
                try:
                    got = node.executor.execute(
                        "i", "Count(Row(f=1))", opt=opt)[0]
                except Exception:
                    with lock:
                        counts["error"] += 1
                    continue
                missing = sorted(opt.missing or ())
                want = total - sum(truth.get(s, 0) for s in missing)
                if got != want:
                    with lock:
                        wrong.append(
                            f"got {got}, want {want} "
                            f"(missing={missing})")
                else:
                    with lock:
                        counts["partial_ok" if missing else "ok"] += 1

        def writer():
            i = 0
            while not stop.is_set():
                i += 1
                col = (i % 6) * SHARD_WIDTH + 5000 + i
                try:
                    nodes[0].executor.execute("i", f"Set({col}, f=2)")
                except Exception:
                    pass  # writes may fail while an owner is down
                stop.wait(0.01)

        threads = ([threading.Thread(target=flapper, daemon=True),
                    threading.Thread(target=writer, daemon=True)]
                   + [threading.Thread(target=reader, args=(p,),
                                       daemon=True)
                      for p in (False, False, True, True)])
        for t in threads:
            t.start()
        time.sleep(2.5)
        stop.set()
        for t in threads:
            t.join(timeout=10)

        assert not wrong, f"silently wrong responses: {wrong[:5]}"
        done = counts["ok"] + counts["partial_ok"] + counts["error"]
        assert done > 20, f"soak produced too little traffic: {counts}"
        goodput = (counts["ok"] + counts["partial_ok"]) / done
        # replica_n=2 keeps every shard reachable through the flap, so
        # reads fail over (or degrade partially) instead of erroring
        assert goodput >= 0.8, f"goodput {goodput:.2f}: {counts}"


# ------------------------------------------------- failpoint integrations


class TestFailpointIntegrations:
    def test_map_shard_failpoint_ticks_on_per_shard_path(self, tmp_path):
        """The executor.map_shard site lives on the per-shard map (the
        fused all-shard paths batch around it): a single-shard
        restriction routes it, and an injected delay passes through
        without changing the result."""
        transport, nodes = make_cluster(tmp_path, n=1)
        truth = _seed_rows(nodes, n_shards=4)
        ex = nodes[0].executor
        fi.arm("executor.map_shard=delay(5)")
        got = ex.execute("i", "Count(Row(f=1))", shards=[0])[0]
        assert got == truth[0]
        assert fi.snapshot()["points"]["executor.map_shard"]["calls"] > 0

    def test_resultcache_fill_failpoint_counts(self):
        from pilosa_tpu.runtime.resultcache import Key, ResultCache

        rc = ResultCache()
        fi.arm("resultcache.fill=error*1")
        with pytest.raises(fi.FailpointError):
            rc.put(Key(("k",)), (1,), "v", 64)
        assert rc.put(Key(("k",)), (1,), "v", 64)  # *1 exhausted
        assert fi.snapshot()["points"]["resultcache.fill"]["triggers"] == 1
