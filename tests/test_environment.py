"""Sanity checks that the virtual multi-device test platform stuck."""

import jax

import pilosa_tpu


def test_eight_virtual_devices():
    assert jax.device_count() == 8
    assert jax.devices()[0].platform == "cpu"


def test_small_shard_width():
    assert pilosa_tpu.SHARD_WIDTH == 1 << 16
