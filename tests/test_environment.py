"""Sanity checks that the virtual multi-device test platform stuck."""

import jax

import pilosa_tpu


def test_eight_virtual_devices():
    assert jax.device_count() == 8
    assert jax.devices()[0].platform == "cpu"


def test_small_shard_width():
    # conftest defaults the suite to 2^16; a width-matrix run (the
    # reference's SHARD_WIDTH CI job) may override the exponent
    import os

    exp = int(os.environ.get("PILOSA_TPU_SHARD_WIDTH_EXP", "16"))
    assert pilosa_tpu.SHARD_WIDTH == 1 << exp
