"""Tiered residency: HBM -> host-RAM -> disk demotion, async
promotion, predictive prefetch, and graceful degradation under memory
pressure (runtime/residency.py, runtime/prefetch.py).

The contract under test: a working set LARGER than the HBM budget
serves with zero failed queries and zero unbounded stalls — eviction
demotes instead of drops, misses promote asynchronously (bounded by
the request deadline; past it the host-compute fallback answers), and
every result is bit-exact against the fully-resident oracle.  The
``?notiers=1`` escape routes the exact pre-tier behavior."""

from __future__ import annotations

import threading
import time
import urllib.request

import numpy as np
import pytest

from pilosa_tpu import faultinject, observe
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.parallel.executor import ExecOptions, Executor
from pilosa_tpu.runtime import residency
from pilosa_tpu.runtime.prefetch import Prefetcher
from pilosa_tpu.serve import deadline as _deadline
from pilosa_tpu.shardwidth import SHARD_WIDTH


@pytest.fixture(autouse=True)
def _disarm():
    yield
    faultinject.disarm()


def _entry_value(payload):
    """Synthetic promote closure: the owner-cache entry is the payload
    itself tagged with a token slot (mirrors the (gens, dev) shape)."""
    return ("tok", payload)


class _SyntheticOwner:
    """A bare owner cache exercising the manager contract without the
    field layer: admit with host payloads, evict, look up, promote."""

    def __init__(self, mgr: residency.ResidencyManager):
        self.mgr = mgr
        self.cache: dict = {}

    def put(self, key, nbytes=100, token="tok"):
        payload = np.zeros(max(1, nbytes // 8), dtype=np.uint64)
        self.cache[key] = ("tok", payload)
        self.mgr.admit(self.cache, key, nbytes, token=token,
                       host=payload, promote=_entry_value)


class TestManagerTiers:
    def test_evict_demotes_into_host_tier(self):
        m = residency.ResidencyManager(250)
        o = _SyntheticOwner(m)
        for i in range(5):
            o.put(i)
        st = m.stats()
        assert m.evictions >= 3
        # demoted entries kept their host bytes
        assert st["tiers"]["demotions"] == m.evictions
        assert st["tiers"]["host"]["entries"] == 5  # resident + demoted
        ent = m.host_lookup(o.cache, 0, "tok")
        assert ent is not None and ent.payload is not None
        assert m.stats()["tiers"]["hits"] == 1

    def test_host_lookup_token_mismatch_drops(self):
        m = residency.ResidencyManager(100)
        o = _SyntheticOwner(m)
        o.put("k", nbytes=80, token=("uid", 1))
        o.put("k2", nbytes=80)  # evicts k
        assert "k" not in o.cache
        assert m.host_lookup(o.cache, "k", ("uid", 2)) is None
        assert m.stats()["tiers"]["misses"] == 1
        # the stale entry was dropped on sight
        assert m.host_lookup(o.cache, "k", ("uid", 1)) is None

    def test_forget_drops_host_twin_demote_keeps_it(self):
        m = residency.ResidencyManager(1000)
        o = _SyntheticOwner(m)
        o.put("a")
        o.put("b")
        m.forget(o.cache, "a")
        assert m.host_lookup(o.cache, "a", "tok") is None
        o.cache.pop("b")
        m.demote(o.cache, "b")
        assert m.host_lookup(o.cache, "b", "tok") is not None
        assert m.stats()["tiers"]["demotions"] >= 1

    def test_host_budget_overflow_drops_without_disk(self):
        residency.configure(host_budget_bytes=250)
        m = residency.ResidencyManager(100)
        o = _SyntheticOwner(m)
        for i in range(6):
            o.put(i)
        st = m.stats()["tiers"]
        assert st["host"]["bytes"] <= 250
        assert st["spillDrops"] >= 1
        assert st["spills"] == 0

    def test_disk_spill_round_trip(self, tmp_path):
        residency.configure(host_budget_bytes=250,
                            disk_path=str(tmp_path / "spill"))
        m = residency.ResidencyManager(100)
        o = _SyntheticOwner(m)
        for i in range(6):
            o.put(i)
        st = m.stats()["tiers"]
        assert st["spills"] >= 1
        assert st["disk"]["entries"] >= 1
        # the oldest entries went to disk; a lookup reloads them
        spilled = [eid for eid in list(m._disk)]
        key = spilled[0][1]
        ent = m.host_lookup(o.cache, key, "tok")
        assert ent is not None and ent.payload is not None
        assert m.stats()["tiers"]["diskHits"] == 1
        # files are cleaned up on close
        m.close()
        assert list((tmp_path / "spill").glob("*.npz")) == []

    def test_notiers_scope_disables_demotion_and_lookup(self):
        m = residency.ResidencyManager(100)
        o = _SyntheticOwner(m)
        with residency.no_tiers():
            assert not residency.tiers_enabled()
            o.put("a", nbytes=80)
            o.put("b", nbytes=80)  # evicts a: DROPPED, not demoted
            assert m.host_lookup(o.cache, "a", "tok") is None
        assert m.stats()["tiers"]["demotions"] == 0
        assert m.stats()["tiers"]["host"]["entries"] == 0

    def test_oom_feedback_shrinks_budget_with_floor(self):
        m = residency.ResidencyManager(1 << 30)
        m.note_oom_feedback()
        assert m.budget == int((1 << 30) * 0.9)
        assert m.oom_budget_shrinks == 1
        m.budget = residency.MIN_BUDGET_BYTES
        m.note_oom_feedback()
        assert m.budget == residency.MIN_BUDGET_BYTES

    def test_run_with_oom_retry(self, monkeypatch):
        from pilosa_tpu import devobs

        monkeypatch.setattr(residency, "_global",
                            residency.ResidencyManager(64 << 20))
        devobs.reset()
        calls = []

        def flaky():
            calls.append(1)
            if len(calls) == 1:
                raise RuntimeError("RESOURCE_EXHAUSTED: out of HBM")
            return 42

        assert residency.run_with_oom_retry(flaky) == 42
        assert len(calls) == 2
        assert devobs.observer().oom_retries == 1
        assert residency.manager().oom_budget_shrinks == 1
        with pytest.raises(ValueError):
            residency.run_with_oom_retry(
                lambda: (_ for _ in ()).throw(ValueError("no")))


class TestPromoter:
    def test_single_flight_per_key(self):
        m = residency.manager()
        o = _SyntheticOwner(m)
        o.put("k")
        ent = m._host[next(iter(m._host))]
        block = threading.Event()
        orig_promote = ent.promote
        ent.promote = lambda p: (block.wait(5), orig_promote(p))[1]
        p = residency.promoter()
        f1 = p.submit(ent)
        f2 = p.submit(ent)
        assert f1 is f2
        block.set()
        assert f1.event.wait(5)
        assert f1.ok

    def test_full_queue_sheds_prefetch_for_demand(self):
        residency.configure(promote_queue=2, promote_workers=1)
        m = residency.manager()
        o = _SyntheticOwner(m)
        gate = threading.Event()
        ents = []
        for i in range(4):
            o.put(i)
            ent = m.host_lookup(o.cache, i, "tok")
            promote = ent.promote
            ent.promote = (lambda pl, _p=promote:
                           (gate.wait(5), _p(pl))[1])
            ents.append(ent)
        p = residency.promoter()
        # first submit occupies the single worker; two more fill the
        # queue with prefetch work
        f0 = p.submit(ents[0])
        # wait until the (single) worker holds f0, leaving the queue
        # empty — the two prefetch submits below then fill it exactly
        deadline = time.monotonic() + 5
        while p.stats()["queue"] > 0 and time.monotonic() < deadline:
            time.sleep(0.01)
        fp1 = p.submit(ents[1], prefetch=True)
        fp2 = p.submit(ents[2], prefetch=True)
        assert fp1 is not None and fp2 is not None
        # a demand submit over the full queue evicts a queued prefetch
        fd = p.submit(ents[3])
        assert fd is not None
        shed = [f for f in (fp1, fp2) if f.event.is_set() and not f.ok]
        assert len(shed) == 1
        assert p.stats()["prefetchShed"] == 1
        gate.set()
        for f in (f0, fd):
            assert f.event.wait(5)

    def test_admission_saturation_sheds_promotions(self):
        from pilosa_tpu.serve.admission import AdmissionController

        ctrl = AdmissionController(internal_cap=1, internal_queue=1)
        held = ctrl.try_acquire("internal")  # saturate the class
        m = residency.manager()
        o = _SyntheticOwner(m)
        o.put("k")
        ent = m.host_lookup(o.cache, "k", "tok")
        p = residency.promoter()
        p.admission = ctrl
        try:
            fl = p.submit(ent)
            assert fl.event.wait(5)
            assert not fl.ok  # shed, not promoted
            assert fl.error is not None
        finally:
            held.release()
            p.admission = None


def _build_index(n_rows: int, shards: int = 4, fill: int = 1 << 14):
    """A holder with ``n_rows`` dense rows spanning ``shards`` shards
    (fill per shard high enough to stay OFF the compressed-container
    path, so every fused read stages the dense row stacks the tier
    manages)."""
    h = Holder(None)
    idx = h.create_index("i")
    f = idx.create_field("f")
    rng = np.random.default_rng(7)
    oracle = {}
    for row in range(n_rows):
        cols = rng.choice(shards * SHARD_WIDTH, size=fill,
                          replace=False)
        f.import_bits(np.full(len(cols), row), cols)
        oracle[row] = len(cols)
    return h, f, oracle


class TestTierRoundTrip:
    """Demote -> promote round trips through the REAL field/executor
    stack: bit-exact results, correct attribution, bounded waits."""

    def test_working_set_over_budget_bit_exact(self):
        # budget sized for ~2 row stacks; 8 rows cycle through it
        residency.reset(2 * 8 * (SHARD_WIDTH // 8) + 1024)
        residency.configure(host_budget_bytes=1 << 30)
        h, _, oracle = _build_index(8)
        ex = Executor(h)
        opt = lambda: ExecOptions(cache=False)  # noqa: E731
        for _ in range(3):
            for row in range(8):
                got = ex.execute("i", f"Count(Row(f={row}))",
                                 opt=opt())[0]
                assert got == oracle[row]
        st = residency.manager().stats()["tiers"]
        assert st["demotions"] > 0, "budget never demoted"
        assert st["hits"] > 0, "host tier never hit"
        assert residency.promoter().stats()["promotions"] > 0
        assert st["fallbacks"] == 0 or st["hits"] > st["fallbacks"]

    def test_notiers_byte_identical(self):
        residency.reset(2 * 8 * (SHARD_WIDTH // 8) + 1024)
        residency.configure(host_budget_bytes=1 << 30)
        h, _, oracle = _build_index(6)
        ex = Executor(h)
        rows_on = {}
        for row in range(6):
            r = ex.execute("i", f"Row(f={row})",
                           opt=ExecOptions(cache=False))[0]
            rows_on[row] = {s: w.copy() for s, w in r.segments.items()}
        before = residency.manager().stats()["tiers"]
        rows_off = {}
        for row in range(6):
            r = ex.execute("i", f"Row(f={row})",
                           opt=ExecOptions(cache=False, tiers=False))[0]
            rows_off[row] = {s: w.copy() for s, w in r.segments.items()}
        after = residency.manager().stats()["tiers"]
        # byte-identical results
        for row in range(6):
            assert rows_on[row].keys() == rows_off[row].keys()
            for s in rows_on[row]:
                assert np.array_equal(rows_on[row][s], rows_off[row][s])
        # the escape really bypassed the tier: no new hits/promotions
        assert after["hits"] == before["hits"]
        assert after["fallbacks"] == before["fallbacks"]

    def test_warm_entry_never_pays_promotion(self):
        residency.reset(64 << 20)  # plenty: everything stays resident
        residency.configure(host_budget_bytes=1 << 30)
        h, _, oracle = _build_index(3)
        ex = Executor(h)
        ex.execute("i", "Count(Row(f=1))",
                   opt=ExecOptions(cache=False))
        observe.take_last()
        ex.execute("i", "Count(Row(f=1))",
                   opt=ExecOptions(cache=False))
        rec = observe.take_last()
        assert rec is not None
        d = rec.to_dict()
        assert "tier" in d
        assert d["tier"]["hbm"] > 0
        assert d["tier"]["promoted"] == 0
        assert d["tier"]["fallback"] == 0
        assert d["tier"]["cold"] == 0

    def test_cold_read_attributed(self):
        residency.reset(64 << 20)
        residency.configure(host_budget_bytes=1 << 30)
        h, _, oracle = _build_index(2)
        ex = Executor(h)
        ex.execute("i", "Count(Row(f=0))", opt=ExecOptions(cache=False))
        rec = observe.take_last()
        assert rec is not None and rec.to_dict()["tier"]["cold"] > 0

    def test_promotion_delay_bounded_by_deadline_fallback(self):
        """A cold-tier read under an injected promotion stall answers
        inside its deadline via the host-compute fallback — the
        zero-unbounded-stalls half of the acceptance criteria."""
        residency.reset(2 * 8 * (SHARD_WIDTH // 8) + 1024)
        residency.configure(host_budget_bytes=1 << 30,
                            promote_wait_ms=5000.0)
        h, _, oracle = _build_index(8)
        ex = Executor(h)
        for _ in range(2):  # populate + demote
            for row in range(8):
                ex.execute("i", f"Count(Row(f={row}))",
                           opt=ExecOptions(cache=False))
        faultinject.arm("residency.promote=delay(400)")
        dl = _deadline.Deadline(0.15)
        t0 = time.perf_counter()
        with _deadline.scope(dl):
            got = ex.execute("i", "Count(Row(f=0))",
                             opt=ExecOptions(cache=False,
                                             deadline=dl))[0]
        elapsed = time.perf_counter() - t0
        rec = observe.take_last()
        assert got == oracle[0]
        # never parked the full 5s promote wait nor the 400ms delay
        # per access: the wait capped at the deadline's remainder
        assert elapsed < 1.0
        assert rec is not None
        assert rec.to_dict()["tier"]["fallback"] > 0
        assert residency.manager().stats()["tiers"]["fallbacks"] > 0

    def test_promotion_failure_falls_back_bit_exact(self):
        residency.reset(2 * 8 * (SHARD_WIDTH // 8) + 1024)
        residency.configure(host_budget_bytes=1 << 30)
        h, _, oracle = _build_index(8)
        ex = Executor(h)
        for _ in range(2):
            for row in range(8):
                ex.execute("i", f"Count(Row(f={row}))",
                           opt=ExecOptions(cache=False))
        faultinject.arm("residency.promote=error")
        for row in range(8):
            got = ex.execute("i", f"Count(Row(f={row}))",
                             opt=ExecOptions(cache=False))[0]
            assert got == oracle[row]
        assert residency.promoter().stats()["failures"] > 0
        assert residency.manager().stats()["tiers"]["fallbacks"] > 0


class TestPrefetcher:
    def test_run_once_promotes_hottest_candidates(self):
        residency.configure(host_budget_bytes=1 << 30)
        m = residency.manager()
        o = _SyntheticOwner(m)
        for i in range(6):
            o.put(i, nbytes=100)
            o.cache.pop(i)
            m.demote(o.cache, i)
        # entry 3 is hot in the flight recorder's access table
        for _ in range(10):
            observe.note_access((id(o.cache), 3))
        p = Prefetcher()
        n = p.run_once()
        assert n >= 1
        deadline = time.monotonic() + 5
        while 3 not in o.cache and time.monotonic() < deadline:
            time.sleep(0.01)
        assert 3 in o.cache  # the hot entry came back resident
        stats = residency.promoter().stats()
        assert stats["prefetchIssued"] >= 1
        assert stats["prefetchCompleted"] >= 1
        # a query touching the prefetched entry counts as useful
        m.touch(o.cache, 3)
        assert m.stats()["tiers"]["prefetchUseful"] == 1

    def test_zero_score_candidates_not_prefetched(self):
        residency.configure(host_budget_bytes=1 << 30)
        m = residency.manager()
        o = _SyntheticOwner(m)
        o.put("unseen", nbytes=100)
        o.cache.pop("unseen")
        m.demote(o.cache, "unseen")
        assert Prefetcher().run_once() == 0


class TestConcurrentChurn:
    """Demote/promote under concurrent mesh dispatch and a racing
    compactor: readers stay bit-exact while generation churn (delta
    merges bump _gen, invalidating every stack token) and tier churn
    (tiny budget) interleave."""

    def test_reads_exact_under_compactor_and_concurrent_dispatch(self):
        from pilosa_tpu import ingest
        from pilosa_tpu.models.view import VIEW_STANDARD

        residency.reset(2 * 8 * (SHARD_WIDTH // 8) + 1024)
        residency.configure(host_budget_bytes=1 << 30)
        h, f, oracle = _build_index(6)
        ingest.configure(delta_enabled=True)
        ex = Executor(h)
        stop = threading.Event()
        errors: list = []

        def reader(seed: int):
            rng = np.random.default_rng(seed)
            try:
                while not stop.is_set():
                    row = int(rng.integers(0, 6))
                    got = ex.execute("i", f"Count(Row(f={row}))",
                                     opt=ExecOptions(cache=False))[0]
                    if got != oracle[row]:
                        errors.append((row, got, oracle[row]))
                        return
            except Exception as e:  # noqa: BLE001 — surfaced below
                errors.append(repr(e))

        def writer():
            # delta writes to rows OUTSIDE the read set, flushed
            # aggressively: every flush bumps _gen, invalidating the
            # read rows' stack tokens mid-churn
            view = f.view(VIEW_STANDARD)
            i = 0
            try:
                while not stop.is_set():
                    frag = view.fragment(i % 4)
                    if frag is not None:
                        frag.import_positions(
                            [100 * SHARD_WIDTH // 4 + i % 1000])
                        frag.flush_delta()
                    i += 1
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

        threads = [threading.Thread(target=reader, args=(s,))
                   for s in range(3)]
        threads.append(threading.Thread(target=writer))
        for t in threads:
            t.start()
        time.sleep(2.0)
        stop.set()
        for t in threads:
            t.join(timeout=30)
            assert not t.is_alive(), "churn thread wedged"
        assert not errors, errors


@pytest.fixture
def tier_server(tmp_path):
    """A server over a deliberately tiny HBM budget: the acceptance
    scenario's 'working set >= 4x HBM' is engineered by budget, not by
    data volume (CI-friendly)."""
    from pilosa_tpu.server.server import Server

    budget = 6 * 8 * (SHARD_WIDTH // 8)  # ~6 padded 4-shard stacks
    residency.reset(budget)
    s = Server(str(tmp_path / "node0"),
               residency_host_budget_bytes=1 << 30,
               residency_prefetch_interval=0.05)
    s.open()
    yield s, budget
    s.close()


class TestAcceptanceWorkingSet:
    """THE acceptance pin: a working set >= 4x the HBM budget serves
    the loadgen read mix with zero failed queries, warm-entry reads
    never pay a promotion, and every result is bit-exact vs the
    fully-resident oracle."""

    def test_4x_working_set_zero_failures_bit_exact(self, tier_server):
        import json

        from tools.loadgen import run_working_set

        s, budget = tier_server
        _post(s.uri, "/index/i")
        _post(s.uri, "/index/i/field/ws")
        report = run_working_set(s.uri, "i", factor=4.0, qps=60.0,
                                 seconds=3.0, shards=4)
        # the index really exceeded HBM 4x
        assert report["working_set_bytes"] >= 4 * budget
        # zero failed queries, zero unbounded stalls
        assert report["errors"] == 0
        assert report["shed"] == 0
        assert report["ok"] == report["sent"]
        # the tier engaged: demotions happened, and SOME reads were
        # served warm (the zipfian head stays resident / prefetched)
        assert (report["server"]["residency.tier.demotions"] or 0) > 0
        warm = report["tiers"].get("warm", {}).get("ok", 0)
        assert warm > 0
        # bit-exact vs the fully-resident oracle: every row carries
        # exactly one bit per shard by construction
        for row in range(0, report["rows"],
                         max(1, report["rows"] // 16)):
            body = json.dumps(
                {"query": f"Count(Row(ws={row}))"}).encode()
            req = urllib.request.Request(
                f"{s.uri}/index/i/query?nocache=1", data=body,
                method="POST")
            req.add_header("Content-Type", "application/json")
            with urllib.request.urlopen(req, timeout=10) as resp:
                got = json.loads(resp.read())["results"][0]
            assert got == 4, (row, got)

    def test_http_surfaces_and_notiers(self, tier_server):
        import json

        s, _ = tier_server
        _post(s.uri, "/index/i")
        _post(s.uri, "/index/i/field/f")
        for col in range(0, 4 * SHARD_WIDTH, SHARD_WIDTH // 64):
            pass  # bulk import below instead
        rows = []
        cols = []
        rng = np.random.default_rng(3)
        for row in range(4):
            cc = rng.choice(4 * SHARD_WIDTH, size=1 << 13,
                            replace=False)
            rows += [row] * len(cc)
            cols += [int(c) for c in cc]
        _post(s.uri, "/index/i/field/f/import",
              {"rowIDs": rows, "columnIDs": cols})
        q = {"query": "Count(Row(f=1))"}
        a = _post(s.uri, "/index/i/query?nocache=1", q)
        b = _post(s.uri, "/index/i/query?nocache=1&notiers=1", q)
        assert a["results"] == b["results"]
        # profile carries the tier attribution
        p = _post(s.uri, "/index/i/query?nocache=1&profile=1", q)
        assert "tier" in (p.get("profile") or {})
        # /debug/devices carries the tier + promoter state
        d = _get(s.uri, "/debug/devices")
        assert "tiers" in d["residency"]
        assert "promoter" in d["residency"]
        assert "host" in d["residency"]["tiers"]
        # /debug/mesh carries the host-tier line
        dm = _get(s.uri, "/debug/mesh")
        assert "hostTierBytes" in dm["residency"]
        # /metrics renders the residency_tier_* and prefetch_* families
        from tools.check_metrics import check_families

        text = _get(s.uri, "/metrics", expect_json=False).decode()
        fams = check_families(text, ("residency_tier_", "prefetch_"))
        assert fams["residency_tier_"] > 0
        assert fams["prefetch_"] > 0


def _post(uri, path, obj=None):
    import json

    body = json.dumps(obj or {}).encode()
    req = urllib.request.Request(uri + path, data=body, method="POST")
    req.add_header("Content-Type", "application/json")
    with urllib.request.urlopen(req, timeout=30) as resp:
        return json.loads(resp.read() or b"null")


def _get(uri, path, expect_json=True):
    import json

    with urllib.request.urlopen(uri + path, timeout=10) as resp:
        data = resp.read()
    return json.loads(data) if expect_json else data
