"""Time-quantum range semantics pinned against the reference's rule:
viewsByTimeRange (time.go:104-180) covers whole units only, so the
effective range floors BOTH ends to the quantum's finest unit — a
mid-unit start includes its whole containing unit and a trailing
partial unit drops.  Randomized over quanta/timestamps/ranges."""

from __future__ import annotations

import datetime as dt
import random

import pytest

from pilosa_tpu.models.field import FieldOptions
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.parallel.executor import Executor
from pilosa_tpu.shardwidth import SHARD_WIDTH


def floor_unit(t: dt.datetime, unit: str) -> dt.datetime:
    if unit == "H":
        return t.replace(minute=0, second=0, microsecond=0)
    if unit == "D":
        return t.replace(hour=0, minute=0, second=0, microsecond=0)
    if unit == "M":
        return t.replace(day=1, hour=0, minute=0, second=0, microsecond=0)
    return t.replace(month=1, day=1, hour=0, minute=0, second=0,
                     microsecond=0)


@pytest.mark.parametrize("seed,quantum", [
    (80, "YM"), (81, "YMDH"), (82, "YMD"), (83, "Y"), (84, "MD"),
    (85, "D"), (86, "DH"),
])
def test_range_floors_to_finest_unit(tmp_path, seed, quantum):
    rng = random.Random(seed)
    finest = quantum[-1]
    holder = Holder(str(tmp_path / "h"))
    idx = holder.create_index("i")
    f = idx.create_field("t", FieldOptions.time_field(quantum))
    events = []
    for _ in range(120):
        c = rng.randrange(2 * SHARD_WIDTH)
        ts = dt.datetime(2020 + rng.randrange(3), rng.randrange(1, 13),
                         rng.randrange(1, 28), rng.randrange(24))
        events.append((c, ts))
        f.set_bit(5, c, ts)
    ex = Executor(holder)
    for _ in range(8):
        a = dt.datetime(2019 + rng.randrange(5), rng.randrange(1, 13),
                        rng.randrange(1, 28), rng.randrange(24))
        b = a + dt.timedelta(days=rng.randrange(1, 700))
        fa, fb = floor_unit(a, finest), floor_unit(b, finest)
        q = (f"Row(t=5, from='{a.strftime('%Y-%m-%dT%H:%M')}', "
             f"to='{b.strftime('%Y-%m-%dT%H:%M')}')")
        want = {c for c, ts in events if fa <= ts < fb}
        got = set(int(x) for x in ex.execute("i", q)[0].columns())
        assert got == want, (q, sorted(got ^ want)[:5])
    holder.close()
