"""Global WAL fd budget (runtime/filebudget.py) — the reference's
syswrap file-count cap (syswrap/os.go:41): past the cap, LRU fds close
behind the scenes and reopen transparently on the next append, so a
10B-scale holder (~9.5k fragments) cannot blow ``ulimit -n``.

Tiers: handle/LRU unit behavior, fragment WAL durability across
evictions and snapshots, and a subprocess that opens far more
fragments than a LOWERED ``RLIMIT_NOFILE`` allows (the VERDICT #4
acceptance shape)."""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

from pilosa_tpu.models.fragment import Fragment
from pilosa_tpu.runtime import filebudget


@pytest.fixture
def budget():
    """A private budget instance patched in as the module global, so
    the cap changes here never leak into other tests."""
    old = filebudget._budget
    b = filebudget.FileBudget(4)
    filebudget._budget = b
    yield b
    filebudget._budget = old


class TestBudgetUnit:
    def test_lru_eviction_and_reopen(self, budget, tmp_path):
        handles = [filebudget.open_append(str(tmp_path / f"w{i}"))
                   for i in range(10)]
        assert budget.open_count() <= 4
        assert budget.evictions >= 6
        for rnd in range(3):
            for i, h in enumerate(handles):
                h.write(f"{rnd}:{i};".encode())
                assert budget.open_count() <= 4
        assert budget.reopens > 0
        for h in handles:
            h.close()
        assert budget.open_count() == 0
        for i in range(10):
            data = (tmp_path / f"w{i}").read_bytes()
            assert data == f"0:{i};1:{i};2:{i};".encode(), i

    def test_truncate_only_on_first_open(self, budget, tmp_path):
        p = str(tmp_path / "t")
        h = filebudget.open_append(p, truncate=True)
        h.write(b"abc")
        # force eviction of h, then write again: must APPEND, not
        # re-truncate
        extra = [filebudget.open_append(str(tmp_path / f"x{i}"))
                 for i in range(4)]
        h.write(b"def")
        h.close()
        for e in extra:
            e.close()
        assert (tmp_path / "t").read_bytes() == b"abcdef"

    def test_write_after_close_fails_loudly(self, budget, tmp_path):
        h = filebudget.open_append(str(tmp_path / "c"))
        h.close()
        with pytest.raises(ValueError, match="closed"):
            h.write(b"x")

    def test_rename_to_follows_evicted_handle(self, budget, tmp_path):
        h = filebudget.open_append(str(tmp_path / "old"), truncate=True)
        h.write(b"one;")
        # evict h, then rename: the reopen after the rename must hit
        # the NEW path (a stale reopen would resurrect "old")
        extra = [filebudget.open_append(str(tmp_path / f"y{i}"))
                 for i in range(4)]
        h.rename_to(str(tmp_path / "new"))
        h.write(b"two;")
        h.close()
        for e in extra:
            e.close()
        assert (tmp_path / "new").read_bytes() == b"one;two;"
        assert not (tmp_path / "old").exists()

    def test_set_cap_shrinks_live(self, budget, tmp_path):
        handles = [filebudget.open_append(str(tmp_path / f"s{i}"))
                   for i in range(4)]
        assert budget.open_count() == 4
        budget.set_cap(2)
        assert budget.open_count() <= 2
        for h in handles:
            h.write(b"z")  # all still writable via reopen
            h.close()

    def test_prometheus_lines(self, budget, tmp_path):
        h = filebudget.open_append(str(tmp_path / "m"))
        text = filebudget.prometheus_lines()
        assert "pilosa_tpu_wal_fd_cap 4" in text
        assert "pilosa_tpu_wal_fd_open 1" in text
        h.close()


class TestFragmentUnderBudget:
    def test_wal_durability_across_evictions(self, budget, tmp_path):
        """More fragments than the cap, interleaved writes; every bit
        must survive a reopen (the WAL append path reopens evicted fds
        transparently)."""
        frags = [Fragment(str(tmp_path / f"f{i}"), "i", "f", "standard", i)
                 for i in range(9)]
        for rnd in range(4):
            for i, fr in enumerate(frags):
                fr.set_bit(rnd, i * fr.width + 17 * i + rnd)
        assert budget.open_count() <= 4
        assert budget.reopens > 0
        for fr in frags:
            fr.close()
        for i in range(9):
            fr = Fragment(str(tmp_path / f"f{i}"), "i", "f", "standard", i)
            for rnd in range(4):
                assert fr.bit(rnd, i * fr.width + 17 * i + rnd), \
                    (i, rnd)
            fr.close()

    def test_snapshot_overflow_rename_with_eviction(self, budget,
                                                    tmp_path):
        """The snapshot's phase-3 overflow-segment commit renames the
        WAL while the budgeted handle may be evicted — acked appends
        must never strand in a resurrected .wal.new."""
        fr = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        for i in range(50):
            fr.set_bit(0, i)
        fr.snapshot()
        # evict the fragment's (post-snapshot) WAL handle
        extra = [filebudget.open_append(str(tmp_path / f"e{i}"))
                 for i in range(4)]
        for i in range(50, 80):
            fr.set_bit(1, i)  # appends via reopen on the RENAMED path
        for e in extra:
            e.close()
        fr.close()
        assert not os.path.exists(str(tmp_path / "frag") + ".wal.new")
        re = Fragment(str(tmp_path / "frag"), "i", "f", "standard", 0)
        assert all(re.bit(0, i) for i in range(50))
        assert all(re.bit(1, i) for i in range(50, 80))
        re.close()


_RLIMIT_SCRIPT = r"""
import os, resource, sys
sys.path.insert(0, sys.argv[1])
os.environ["PILOSA_TPU_MAX_WAL_FILES"] = "64"
soft, hard = resource.getrlimit(resource.RLIMIT_NOFILE)
resource.setrlimit(resource.RLIMIT_NOFILE, (min(256, hard), hard))

from pilosa_tpu.models.holder import Holder
from pilosa_tpu.runtime import filebudget
from pilosa_tpu.shardwidth import SHARD_WIDTH

d = sys.argv[2]
h = Holder(d)
idx = h.create_index("i")
# 2 fields x 200 shards = 400 fragments, far over both the 64-fd
# budget and what a 256 RLIMIT_NOFILE could hold un-budgeted
for fname in ("a", "b"):
    f = idx.create_field(fname)
    rows = [0] * 200 + [1] * 200
    cols = [s * SHARD_WIDTH + 7 for s in range(200)] * 2
    f.import_bits(rows, cols)
assert filebudget.budget().open_count() <= 64, \
    filebudget.budget().open_count()
assert filebudget.budget().evictions > 0
# every fragment answers, and a second write round still lands
for fname in ("a", "b"):
    f = idx.field(fname)
    for s in range(200):
        f.set_bit(2, s * SHARD_WIDTH + 9)
h.close()

h2 = Holder(d)
idx2 = h2.index("i")
from pilosa_tpu.ops.bitmap import unpack_positions
for fname in ("a", "b"):
    f2 = idx2.field(fname)
    for s in (0, 99, 199):
        assert list(unpack_positions(f2.row(0, s))) == [7], (fname, s)
        assert list(unpack_positions(f2.row(2, s))) == [9], (fname, s)
h2.close()
print("RLIMIT-OK", flush=True)
# skip interpreter teardown: with the lowered RLIMIT still in force,
# native-runtime atexit threads (XLA/BLAS) can die in C++ unwinding
# AFTER everything under test has passed and closed cleanly
os._exit(0)
"""


def test_many_fragments_under_lowered_rlimit(tmp_path):
    """VERDICT #4 acceptance: open far more fragments than the fd cap
    under a lowered RLIMIT_NOFILE; the budget must keep the process
    under the limit with every write durable."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, PALLAS_AXON_POOL_IPS="")
    out = subprocess.run(
        [sys.executable, "-c", _RLIMIT_SCRIPT, repo, str(tmp_path / "h")],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stdout[-2000:] + out.stderr[-2000:]
    assert "RLIMIT-OK" in out.stdout
