"""Background snapshot queue tests (reference holder.go:163 queue +
fragment.go:187-208 workers).

Three guarantees: writes past the opN threshold do not stall on
compaction; crash at any point around a background snapshot loses
nothing (WAL-carried durability); the queue de-duplicates and drains."""

import os
import time

import pytest

from pilosa_tpu.models.fragment import Fragment
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.runtime import snapqueue
from pilosa_tpu.shardwidth import SHARD_WIDTH


def _mk(path, max_op_n=50):
    return Fragment(str(path), "i", "f", "standard", 0, max_op_n=max_op_n)


def test_writes_do_not_stall_on_compaction(tmp_path):
    """Writes landing WHILE a snapshot's file I/O runs must not block on
    it: the two-phase snapshot only holds the fragment lock for the
    matrix copy, writers append to the overflow WAL segment during the
    fsync (the old design held the lock across the whole rewrite)."""
    import threading
    from unittest import mock

    frag = _mk(tmp_path / "frag", max_op_n=10_000)
    for r in range(64):
        frag.set_bit(r, (r * 37) % SHARD_WIDTH)

    # make phase 2 (outside the lock) measurably slow
    real_fsync = os.fsync
    in_phase2 = threading.Event()

    def slow_fsync(fd):
        in_phase2.set()
        time.sleep(0.5)
        real_fsync(fd)

    with mock.patch("os.fsync", side_effect=slow_fsync):
        t = threading.Thread(target=frag.snapshot)
        t.start()
        assert in_phase2.wait(timeout=10)
        # snapshot is mid-fsync now; a write must complete immediately
        t0 = time.perf_counter()
        frag.set_bit(0, 12345)
        write_cost = time.perf_counter() - t0
        t.join()
    assert write_cost < 0.25, write_cost  # did not wait for the 0.5s fsync
    # and the concurrent write survived the WAL-segment swap
    frag2 = _mk(tmp_path / "frag")
    assert frag2.bit(0, 12345)
    frag2.close()
    frag.close()


def test_failed_snapshot_folds_overflow_back(tmp_path):
    """If the snapshot write fails, the ops that were only in the old
    WAL must stay durable: the overflow segment folds back in and
    appending resumes on the main WAL."""
    from unittest import mock

    frag = _mk(tmp_path / "frag", max_op_n=10_000)
    for i in range(50):
        frag.set_bit(2, i)
    with mock.patch("os.replace", side_effect=OSError("disk full")):
        with pytest.raises(OSError):
            frag.snapshot()
    assert not os.path.exists(str(tmp_path / "frag") + ".wal.new")
    # writes continue on the healed WAL
    frag.set_bit(2, 999)
    frag.close()
    frag2 = _mk(tmp_path / "frag")
    import numpy as np

    assert int(np.bitwise_count(frag2.row(2)).sum()) == 51
    frag2.close()


def test_crash_between_wal_append_and_snapshot_loses_nothing(tmp_path):
    """Write past the threshold, then 'crash' (reopen from the same dir
    WITHOUT close/drain): the queued-but-unfinished compaction must not
    matter — replay restores every bit.  The queue is parked so no live
    worker mutates the files while the 'crashed' copy reads them (a
    real crash has no workers either)."""
    from unittest import mock

    path = tmp_path / "frag"
    with mock.patch.object(snapqueue, "enqueue", lambda f: None):
        frag = _mk(path, max_op_n=50)
        want = set()
        for i in range(180):
            pos = (i * 7919) % SHARD_WIDTH
            frag.set_bit(i % 5, pos)
            want.add((i % 5, pos))
    # compactions were queued (and dropped) but never ran: the WAL is
    # the only durable copy — exactly the crash-before-compaction state
    frag2 = _mk(path, max_op_n=50)
    got = set()
    for r in range(5):
        row = frag2.row(r)
        if row is not None:
            import numpy as np

            for off in np.flatnonzero(
                    np.unpackbits(row.view(np.uint8), bitorder="little")):
                got.add((r, int(off)))
    assert got == want
    snapqueue.drain()
    frag2.close()
    frag.close()


def test_torn_wal_tail_replays_prefix(tmp_path):
    """Crash mid-WAL-append: the torn last record is ignored, every
    complete record replays (reference op-log replay semantics)."""
    path = tmp_path / "frag"
    frag = _mk(path, max_op_n=10_000)  # never snapshots
    for i in range(100):
        frag.set_bit(1, i)
    frag.close()
    wal = str(path) + ".wal"
    size = os.path.getsize(wal)
    with open(wal, "r+b") as f:
        f.truncate(size - 3)  # tear the final record
    frag2 = _mk(path)
    row = frag2.row(1)
    import numpy as np

    count = int(np.bitwise_count(row).sum())
    assert count == 99  # last record torn, prefix intact
    frag2.close()


def test_queue_dedup_and_drain(tmp_path):
    frag = _mk(tmp_path / "frag", max_op_n=5)
    for i in range(50):
        frag.set_bit(0, i)
    # multiple enqueues of the same fragment collapse
    assert snapqueue.pending_count() <= 1
    assert snapqueue.drain(timeout=30)
    assert snapqueue.pending_count() == 0
    # compaction actually happened: WAL truncated below the op run
    assert frag._op_n < 50
    frag.close()


def test_holder_close_drains_queue(tmp_path):
    h = Holder(str(tmp_path / "h"))
    idx = h.create_index("i")
    f = idx.create_field("f")
    view = f.create_view_if_not_exists("standard")
    frag = view.create_fragment_if_not_exists(0)
    frag.max_op_n = 20
    for i in range(100):
        f.set_bit(0, i)
    h.close()  # must drain, then close fragments
    # reopen: snapshot file exists and holds the data
    h2 = Holder(str(tmp_path / "h"))
    from pilosa_tpu.parallel.executor import Executor

    assert Executor(h2).execute("i", "Count(Row(f=0))")[0] == 100
    h2.close()


def test_snapshot_failure_bumps_counter_and_logs(tmp_path):
    """An injected compaction failure must surface in BOTH the
    process-wide counter (alert-able at /metrics) and the logger —
    never print-only (VERDICT round-2 weak #5)."""
    class _RecordingLogger:
        def __init__(self):
            self.lines = []

        def printf(self, fmt, *args):
            self.lines.append(fmt % args if args else fmt)

        def debugf(self, fmt, *args):
            pass

    class _Boom:
        path = "injected-failure-fragment"

        def snapshot(self):
            raise OSError("injected disk failure")

    rec = _RecordingLogger()
    old_log = snapqueue.log
    snapqueue.log = rec
    try:
        before = snapqueue.counters()["snapshot_failures"]
        snapqueue.enqueue(_Boom())
        assert snapqueue.drain(timeout=10)
        assert snapqueue.counters()["snapshot_failures"] == before + 1
    finally:
        snapqueue.log = old_log
    assert any("injected-failure-fragment" in ln and "failed" in ln
               for ln in rec.lines)
    text = snapqueue.prometheus_lines()
    assert "pilosa_snapqueue_snapshot_failures_total" in text


def test_drain_timeout_returns_false_and_bumps_counter():
    """drain() must honor its timeout while a snapshot is wedged (the
    counter bump runs with the condition's lock already held — a
    re-acquire would deadlock exactly on this path)."""
    import threading

    release = threading.Event()

    class _Hang:
        path = "wedged-fragment"

        def snapshot(self):
            release.wait(timeout=30)

    before = snapqueue.counters()["drain_timeouts"]
    snapqueue.enqueue(_Hang())
    try:
        t0 = time.monotonic()
        assert snapqueue.drain(timeout=0.3) is False
        assert time.monotonic() - t0 < 5
        assert snapqueue.counters()["drain_timeouts"] == before + 1
    finally:
        release.set()
    assert snapqueue.drain(timeout=10)


def test_metrics_route_exposes_snapqueue_counters(tmp_path):
    """/metrics on any server carries the process-wide snapshot-queue
    counters (compaction starvation must be dashboard-visible)."""
    import urllib.request

    from pilosa_tpu.server.server import Server

    s = Server(str(tmp_path / "node0"))
    s.open()
    try:
        with urllib.request.urlopen(s.uri + "/metrics", timeout=10) as resp:
            text = resp.read().decode()
    finally:
        s.close()
    assert "pilosa_snapqueue_snapshot_failures_total" in text
    assert "pilosa_snapqueue_drain_timeouts_total" in text


def test_enqueue_on_closed_fragment_is_noop(tmp_path):
    frag = _mk(tmp_path / "frag", max_op_n=5)
    for i in range(20):
        frag.set_bit(0, i)
    frag.close()
    snapqueue.enqueue(frag)  # races close in real life; must not crash
    assert snapqueue.drain(timeout=10)
    assert frag._wal is None  # close state not resurrected
