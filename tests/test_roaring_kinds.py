"""Serialization parity for the three container kinds: wire-type
headers must match ``pick_kind`` exactly (the same rule the device
directory uses, so wire and device kinds can never drift), the 4096
array->bitmap cardinality boundary must flip the header type, run
containers must carry the reference interval payload byte-for-byte,
and the 65535/65536 container-boundary bits must survive round trips
through all three kinds (roaring/roaring.go optimize() +
containerArray/containerBitmap/containerRun)."""

from __future__ import annotations

import numpy as np
import pytest

from pilosa_tpu.storage import roaring as rc

FULL = 65536


def dense(offsets):
    """One dense container (uint64[1024]) with the given bit offsets."""
    w = np.zeros(1024, dtype=np.uint64)
    offs = np.asarray(offsets, dtype=np.int64)
    np.bitwise_or.at(w, offs // 64, np.uint64(1) << (offs % 64).astype(np.uint64))
    return w


def wire_headers(blob):
    """Parse the descriptive-header section -> [(key, typ, card)]."""
    assert int.from_bytes(blob[0:2], "little") == rc.MAGIC
    n = int.from_bytes(blob[4:8], "little")
    out = []
    for i in range(n):
        off = 8 + i * 12
        key = int.from_bytes(blob[off : off + 8], "little")
        typ = int.from_bytes(blob[off + 8 : off + 10], "little")
        card = int.from_bytes(blob[off + 10 : off + 12], "little") + 1
        out.append((key, typ, card))
    return out


def roundtrip_both(keys, words):
    """Encode with native and python, decode each with both decoders,
    assert everything agrees, and return the blob + decoded state."""
    keys = np.asarray(keys, dtype=np.uint64)
    blob = rc.encode(keys, words)
    assert blob == rc._encode_py(keys, np.asarray(words), 0)
    k_n, w_n, _ = rc.decode(blob)
    k_p, w_p, _ = rc._decode_py(blob)
    np.testing.assert_array_equal(k_n, k_p)
    np.testing.assert_array_equal(w_n, w_p)
    return blob, k_n, w_n


@pytest.mark.parametrize("seed", range(4))
def test_wire_headers_match_pick_kind(seed):
    rng = np.random.default_rng(seed)
    rows = []
    for card in rng.choice([1, 7, 100, 3000, 4096, 4097, 20000, FULL], 6, replace=False):
        rows.append(dense(np.sort(rng.choice(FULL, int(card), replace=False))))
    keys = np.arange(len(rows), dtype=np.uint64) * 3
    words = np.stack(rows)
    blob, k2, w2 = roundtrip_both(keys, words)
    np.testing.assert_array_equal(k2, keys)
    np.testing.assert_array_equal(w2, words)
    for (key, typ, card), w in zip(wire_headers(blob), words):
        c, runs = rc.container_stats(w)
        assert card == c, key
        assert typ == rc._WIRE_TYPE[rc.pick_kind(c, runs)], key


def test_array_bitmap_boundary_wire_types():
    # Even offsets make every bit its own run, so the run kind can never
    # undercut the array/bitmap choice: the header type isolates the
    # 2*card <= 8192 rule (ArrayMaxSize).
    at_max = dense(np.arange(0, 2 * 4096, 2))       # card 4096 -> array
    over = dense(np.arange(0, 2 * 4097, 2))         # card 4097 -> bitmap
    blob, _, w2 = roundtrip_both([0, 1], np.stack([at_max, over]))
    assert [t for _, t, _ in wire_headers(blob)] == [1, 2]
    assert [c for _, _, c in wire_headers(blob)] == [4096, 4097]
    np.testing.assert_array_equal(w2[0], at_max)
    np.testing.assert_array_equal(w2[1], over)
    assert rc.pick_kind(4096, 4096) == rc.KIND_ARRAY
    assert rc.pick_kind(4097, 4097) == rc.KIND_BITMAP


def test_full_container_run_payload_golden():
    # All 65536 bits = one run (0, 65535): 6-byte payload beats both the
    # bitmap and the (out-of-range) array.  Pin the exact bytes.
    keys = np.array([5], dtype=np.uint64)
    words = dense(np.arange(FULL)).reshape(1, -1)
    blob, _, w2 = roundtrip_both(keys, words)
    assert wire_headers(blob) == [(5, 3, FULL)]
    want = bytearray()
    want += (12348).to_bytes(2, "little") + bytes([0, 0])
    want += (1).to_bytes(4, "little")
    want += (5).to_bytes(8, "little") + (3).to_bytes(2, "little") + (FULL - 1).to_bytes(2, "little")
    want += (8 + 12 + 4).to_bytes(4, "little")
    want += (1).to_bytes(2, "little")                        # run count
    want += (0).to_bytes(2, "little") + (FULL - 1).to_bytes(2, "little")
    assert blob == bytes(want)
    np.testing.assert_array_equal(w2[0], words[0])


def test_single_bit_and_small_run_kinds():
    # A single bit is one run, but the 6-byte run payload loses to the
    # 2-byte array (the reference picks array too); a long single run
    # wins against both.
    single = dense([12345])
    long_run = dense(np.arange(100, 10100))
    blob, _, _ = roundtrip_both([0, 1], np.stack([single, long_run]))
    assert [t for _, t, _ in wire_headers(blob)] == [1, 3]
    assert rc.pick_kind(1, 1) == rc.KIND_ARRAY
    assert rc.pick_kind(10000, 1) == rc.KIND_RUN


def test_boundary_bits_through_all_kinds():
    # Bits 65535 (last of container 0) and 65536 (first of container 1)
    # must survive round trips no matter which kind each container lands
    # in.  Build the pair so container 0 / container 1 each take on all
    # three kinds across the cases.
    rng = np.random.default_rng(7)

    def as_array(offsets_extra):
        return sorted(set(offsets_extra) | set(rng.choice(FULL, 50, replace=False).tolist()))

    cases = {
        "array": (dense(as_array([FULL - 1])), dense(as_array([0])), 1),
        "bitmap": (
            dense(sorted(set(np.arange(0, FULL, 2).tolist()) | {FULL - 1})),
            dense(np.arange(0, FULL, 2)),  # bit 0 is even, already present
            2,
        ),
        "run": (dense(np.arange(60000, FULL)), dense(np.arange(0, 9000)), 3),
    }
    for name, (c0, c1, want_typ) in cases.items():
        blob, k2, w2 = roundtrip_both([0, 1], np.stack([c0, c1]))
        assert [t for _, t, _ in wire_headers(blob)] == [want_typ, want_typ], name
        pos = rc.containers_to_positions(k2, w2)
        assert FULL - 1 in pos and FULL in pos, name
        np.testing.assert_array_equal(w2[0], c0, err_msg=name)
        np.testing.assert_array_equal(w2[1], c1, err_msg=name)
