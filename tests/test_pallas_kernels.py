"""Pallas kernel tests: interpret-mode runs on CPU diffed against the
jnp reference implementations (the roaring/naive.go oracle pattern)."""

from __future__ import annotations

import numpy as np
import pytest

from pilosa_tpu.ops import bitmap as bm
from pilosa_tpu.ops import pallas_kernels as pk


def _rand_words(rng, *shape):
    return rng.integers(0, 1 << 32, size=shape, dtype=np.uint32)


class TestRowCounts:
    @pytest.mark.parametrize("rows,words", [(1, 64), (7, 100),
                                            (128, 2048), (130, 2049),
                                            (300, 4096)])
    def test_matches_jnp(self, rows, words):
        rng = np.random.default_rng(rows * 1000 + words)
        mat = _rand_words(rng, rows, words)
        filt = _rand_words(rng, words)
        want = np.asarray(bm.row_counts_masked(mat, filt))
        got = np.asarray(pk._row_counts_masked_pallas(mat, filt,
                                                      interpret=True))
        np.testing.assert_array_equal(got, want)

    def test_zero_filter(self):
        mat = _rand_words(np.random.default_rng(0), 8, 256)
        filt = np.zeros(256, dtype=np.uint32)
        got = np.asarray(pk._row_counts_masked_pallas(mat, filt,
                                                      interpret=True))
        assert got.tolist() == [0] * 8

    def test_dispatch_fallback_small(self):
        # tiny inputs use the jnp path regardless of platform
        mat = _rand_words(np.random.default_rng(1), 2, 8)
        filt = _rand_words(np.random.default_rng(2), 8)
        got = np.asarray(pk.row_counts_masked(mat, filt))
        want = np.asarray(bm.row_counts_masked(mat, filt))
        np.testing.assert_array_equal(got, want)


class TestCountAnd:
    @pytest.mark.parametrize("words", [64, 2048, 4096, 5000])
    def test_matches_jnp(self, words):
        rng = np.random.default_rng(words)
        a, b = _rand_words(rng, words), _rand_words(rng, words)
        want = int(bm.popcount_and(a, b))
        got = int(pk._count_and_pallas(a, b, interpret=True))
        assert got == want

    def test_oracle_python_sets(self):
        rng = np.random.default_rng(7)
        pos_a = rng.choice(1 << 16, 500, replace=False)
        pos_b = rng.choice(1 << 16, 500, replace=False)
        a = bm.pack_positions(pos_a, 1 << 16)
        b = bm.pack_positions(pos_b, 1 << 16)
        want = len(set(pos_a) & set(pos_b))
        assert int(pk._count_and_pallas(a, b, interpret=True)) == want


class TestBsiCompare:
    def _planes(self, values, depth, words):
        """Build [2+depth, words] plane stack from {col: value>=0}."""
        P = np.zeros((2 + depth, words * 32), dtype=bool)
        for col, v in values.items():
            P[0, col] = True
            for i in range(depth):
                if (v >> i) & 1:
                    P[2 + i, col] = True
        return np.packbits(P, axis=1, bitorder="little").view(
            np.uint32).reshape(2 + depth, words)

    @pytest.mark.parametrize("depth,pred", [(4, 5), (8, 100), (12, 2048)])
    def test_matches_python_oracle(self, depth, pred):
        rng = np.random.default_rng(depth)
        words = 160
        values = {int(c): int(rng.integers(0, 1 << depth))
                  for c in rng.choice(words * 32, 300, replace=False)}
        planes = self._planes(values, depth, words)
        filt = np.full(words, 0xFFFFFFFF, dtype=np.uint32)
        lt, gt = pk.bsi_compare_unsigned(planes, filt, pred, depth,
                                         interpret=True)
        lt_cols = set(np.asarray(bm.unpack_positions(np.asarray(lt))))
        gt_cols = set(np.asarray(bm.unpack_positions(np.asarray(gt))))
        assert lt_cols == {c for c, v in values.items() if v < pred}
        assert gt_cols == {c for c, v in values.items() if v > pred}

    def test_jnp_fallback_identical(self):
        rng = np.random.default_rng(3)
        depth, words = 6, 160
        values = {int(c): int(rng.integers(0, 1 << depth))
                  for c in rng.choice(words * 32, 100, replace=False)}
        planes = self._planes(values, depth, words)
        filt = np.full(words, 0xFFFFFFFF, dtype=np.uint32)
        lt_p, gt_p = pk._bsi_compare_pallas(
            planes, filt,
            np.array([[0xFFFFFFFF if (9 >> i) & 1 else 0]
                      for i in range(depth)], dtype=np.uint32),
            depth, interpret=True)
        lt_j, gt_j = pk._bsi_compare_jnp(planes, filt, 9, depth)
        np.testing.assert_array_equal(np.asarray(lt_p), np.asarray(lt_j))
        np.testing.assert_array_equal(np.asarray(gt_p), np.asarray(gt_j))

    def test_out_of_range_predicate(self):
        # predicate above 2^depth: everything considered is strictly lt
        depth, words = 4, 160
        values = {10: 3, 50: 15}
        planes = self._planes(values, depth, words)
        filt = np.full(words, 0xFFFFFFFF, dtype=np.uint32)
        lt, gt = pk.bsi_compare_unsigned(planes, filt, 20, depth,
                                         interpret=True)
        lt_cols = set(np.asarray(bm.unpack_positions(np.asarray(lt))))
        assert lt_cols == {10, 50}
        assert int(np.asarray(gt).sum()) == 0

    def test_filter_and_sign_respected(self):
        depth, words = 4, 160
        planes = self._planes({10: 3, 50: 12}, depth, words)
        # column 50 marked negative via the sign plane
        sign = np.zeros(words * 32, dtype=bool)
        sign[50] = True
        planes[1] = np.packbits(sign, bitorder="little").view(
            np.uint32)[:words]
        filt = np.full(words, 0xFFFFFFFF, dtype=np.uint32)
        lt, _ = pk.bsi_compare_unsigned(planes, filt, 100, depth,
                                        interpret=True)
        cols = set(np.asarray(bm.unpack_positions(np.asarray(lt))))
        assert cols == {10}  # negative column excluded from unsigned path


class TestMaskedMatrixCounts:
    @pytest.mark.parametrize("groups,rows,words", [
        (1, 1, 64), (3, 7, 100), (8, 128, 256), (9, 130, 257),
        (17, 200, 512)])
    def test_matches_oracle(self, groups, rows, words):
        rng = np.random.default_rng(groups * 7 + rows)
        mat = _rand_words(rng, rows, words)
        masks = _rand_words(rng, groups, words)
        want = np.bitwise_count(
            mat[None, :, :] & masks[:, None, :]).sum(axis=-1)
        got = np.asarray(pk._mmc_pallas(mat, masks, interpret=True))
        np.testing.assert_array_equal(got, want.astype(np.int32))

    def test_dispatch_wrapper_matches(self):
        import jax.numpy as jnp

        rng = np.random.default_rng(5)
        # device (jnp) inputs above the 2^18 size gate so the wrapper
        # actually takes the Pallas branch (interpret-mode on CPU)
        mat = _rand_words(rng, 300, 512)
        masks = _rand_words(rng, 9, 512)
        got = np.asarray(pk.masked_matrix_counts(
            jnp.asarray(mat), jnp.asarray(masks), interpret=True))
        want = np.asarray(bm.masked_matrix_counts(mat, masks))
        np.testing.assert_array_equal(got, want)
        # below the gate (or host arrays): falls through to bm
        small = np.asarray(pk.masked_matrix_counts(mat[:4], masks[:2],
                                                   interpret=True))
        np.testing.assert_array_equal(
            small, np.asarray(bm.masked_matrix_counts(mat[:4], masks[:2])))

    def test_zero_masks(self):
        mat = np.full((16, 128), 0xFFFFFFFF, dtype=np.uint32)
        masks = np.zeros((4, 128), dtype=np.uint32)
        got = np.asarray(pk._mmc_pallas(mat, masks, interpret=True))
        assert got.sum() == 0


class TestRoutingGate:
    """_use_pallas is the single routing gate all four dispatchers
    share; PILOSA_TPU_PALLAS=0 is the operator escape hatch for a
    Mosaic regression."""

    def test_interpret_always_routes_to_pallas(self, monkeypatch):
        monkeypatch.setattr(pk, "on_tpu", lambda: False)
        assert pk._use_pallas(True, 1)

    def test_small_shapes_stay_on_xla(self, monkeypatch):
        monkeypatch.setattr(pk, "on_tpu", lambda: True)
        # isolate from an ambient operator escape hatch
        monkeypatch.delenv("PILOSA_TPU_PALLAS", raising=False)
        assert not pk._use_pallas(False, (1 << 16) - 1)
        assert pk._use_pallas(False, 1 << 16)

    def test_off_tpu_always_xla(self, monkeypatch):
        monkeypatch.setattr(pk, "on_tpu", lambda: False)
        assert not pk._use_pallas(False, 1 << 30)

    def test_knob_disables_on_tpu(self, monkeypatch):
        monkeypatch.setattr(pk, "on_tpu", lambda: True)
        monkeypatch.setenv("PILOSA_TPU_PALLAS", "0")
        assert not pk._use_pallas(False, 1 << 30)
        monkeypatch.setenv("PILOSA_TPU_PALLAS", "auto")
        assert pk._use_pallas(False, 1 << 30)


def test_pallas_routing_honors_chip_winners(monkeypatch):
    """The dispatch gate routes per-kernel by the committed chip A/B
    (PALLAS_TPU_VALIDATION.json winners): a kernel the chip timed
    slower than XLA's fusion routes to XLA, winners and unmeasured
    kernels route to Pallas, PILOSA_TPU_PALLAS=force/0 override both
    ways (round-5: evidence-driven routing instead of blanket
    on-TPU default)."""
    import pytest

    from pilosa_tpu.ops import pallas_kernels as pk

    monkeypatch.setattr(pk, "on_tpu", lambda: True)
    monkeypatch.delenv("PILOSA_TPU_PALLAS", raising=False)
    winners = pk._kernel_winners()
    if not winners:
        pytest.skip("no timed chip validation artifact committed")
    assert set(winners.values()) <= {"pallas", "xla"}
    for name, w in winners.items():
        assert pk._use_pallas(False, 1 << 30, kernel=name) \
            == (w != "xla"), (name, w)
    # evidence-free kernels keep the on-TPU default
    assert pk._use_pallas(False, 1 << 30, kernel="not-a-kernel")
    # force re-enables losers (the A/B escape hatch)...
    monkeypatch.setenv("PILOSA_TPU_PALLAS", "force")
    assert all(pk._use_pallas(False, 1 << 30, kernel=n) for n in winners)
    # ...and off disables winners
    monkeypatch.setenv("PILOSA_TPU_PALLAS", "0")
    assert not any(pk._use_pallas(False, 1 << 30, kernel=n)
                   for n in winners)
