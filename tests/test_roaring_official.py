"""Official 32-bit roaring format decode (cookies 12346/12347) —
interchange compat with the community format, like the reference's
UnmarshalBinary (roaring/unmarshal_binary.go; golden file
roaring/testdata/bitmapcontainer.roaringbitmap)."""

from __future__ import annotations

import os
import struct

import numpy as np
import pytest

from pilosa_tpu.storage import roaring

GOLDEN = "/root/reference/roaring/testdata/bitmapcontainer.roaringbitmap"


def encode_official(containers, with_runs=False):
    """Hand-build official-format bytes.  containers: list of
    (key16, kind, payload) where kind is 'array' (sorted uint16 list),
    'bitmap' (8KB bytes), or 'run' (list of (start, length-1))."""
    n = len(containers)
    out = b""
    if with_runs:
        out += struct.pack("<HH", 12347, n - 1)
        flags = bytearray((n + 7) // 8)
        for i, (_, kind, _) in enumerate(containers):
            if kind == "run":
                flags[i // 8] |= 1 << (i % 8)
        out += bytes(flags)
    else:
        out += struct.pack("<HHI", 12346, 0, n)
    bodies = []
    for key, kind, payload in containers:
        if kind == "array":
            card = len(payload)
            body = np.asarray(payload, dtype=np.uint16).tobytes()
        elif kind == "bitmap":
            card = int(np.unpackbits(
                np.frombuffer(payload, dtype=np.uint8)).sum())
            body = payload
        else:  # run
            card = sum(length + 1 for _, length in payload)
            body = struct.pack("<H", len(payload)) + b"".join(
                struct.pack("<HH", s, l) for s, l in payload)
        out += struct.pack("<HH", key, card - 1)
        bodies.append(body)
    if not with_runs or n >= 4:
        # offset header
        base = len(out) + 4 * n
        off = base
        for body in bodies:
            out += struct.pack("<I", off)
            off += len(body)
    for body in bodies:
        out += body
    return out


def _positions(keys, words):
    out = set()
    for k, w in zip(keys, words):
        bits = np.unpackbits(w.view(np.uint8), bitorder="little")
        for b in np.nonzero(bits)[0]:
            out.add(int(k) * (1 << 16) + int(b))
    return out


class TestOfficialFormat:
    def test_array_container(self):
        blob = encode_official([(0, "array", [1, 5, 100]),
                                (3, "array", [0])])
        keys, words, _ = roaring.decode(blob)
        assert _positions(keys, words) == {1, 5, 100, 3 * (1 << 16)}

    def test_bitmap_container(self):
        # container type is inferred from cardinality: > 4096 => bitmap
        bits = np.zeros(1 << 16, dtype=bool)
        want_bits = set(range(0, 1 << 16, 8)) | {7, 65535}
        bits[sorted(want_bits)] = True
        payload = np.packbits(bits, bitorder="little").tobytes()
        blob = encode_official([(1, "bitmap", payload)])
        keys, words, _ = roaring.decode(blob)
        assert _positions(keys, words) == {(1 << 16) + b
                                           for b in want_bits}

    def test_run_container(self):
        blob = encode_official([(0, "run", [(10, 4), (100, 0)])],
                               with_runs=True)
        keys, words, _ = roaring.decode(blob)
        assert _positions(keys, words) == {10, 11, 12, 13, 14, 100}

    def test_mixed_with_offset_header(self):
        # >= 4 containers forces the offset section in runs format
        blob = encode_official(
            [(0, "array", [9]), (1, "run", [(0, 2)]),
             (2, "array", [5, 6]), (4, "array", [1])],
            with_runs=True)
        keys, words, _ = roaring.decode(blob)
        want = {9, (1 << 16), (1 << 16) + 1, (1 << 16) + 2,
                2 * (1 << 16) + 5, 2 * (1 << 16) + 6, 4 * (1 << 16) + 1}
        assert _positions(keys, words) == want

    def test_truncations_rejected(self):
        # the container count is fixed in the header, so EVERY proper
        # prefix must raise (never silently decode partial containers)
        blob = encode_official([(0, "array", [1, 2, 3])])
        for cut in range(0, len(blob)):
            with pytest.raises(roaring.RoaringError):
                roaring.decode(blob[:cut])

    @pytest.mark.skipif(not os.path.exists(GOLDEN),
                        reason="reference golden file unavailable")
    def test_reference_golden_file(self):
        """The reference's own official-format compatibility fixture
        must decode (content cross-checked structurally: its first
        container is a dense bitmap)."""
        with open(GOLDEN, "rb") as f:
            blob = f.read()
        keys, words, _ = roaring.decode(blob)
        assert len(keys) >= 1
        positions = _positions(keys, words)
        assert len(positions) > 4096  # bitmap container => dense
        # spot invariants from the file header: 2 containers, first is
        # a nearly-full bitmap starting at bit 1
        assert 0 not in positions and 1 in positions