"""Differential tests: packed-bitmap kernels vs the naive set oracle.

Mirrors the reference's differential-oracle strategy
(roaring/naive_test.go) and its per-density coverage of container types:
sparse (= array containers), dense (= bitmap containers), and runs
(= RLE containers) all map to the same dense packed layout here, but the
test densities are kept to shake out the same edge cases.
"""

import numpy as np
import pytest

from pilosa_tpu.ops import (
    b_and,
    b_andnot,
    b_flip_range,
    b_not,
    b_or,
    b_shift,
    b_xor,
    clear_bits,
    get_bits,
    n_words,
    pack_positions,
    pack_positions_matrix,
    popcount,
    popcount_and,
    reduce_and_rows,
    reduce_or_rows,
    row_counts,
    row_counts_masked,
    set_bits,
    unpack_positions,
)
from tests.naive import NaiveBitmap

NBITS = 1 << 16
RNG = np.random.default_rng(42)


def random_positions(density):
    n = max(1, int(NBITS * density))
    return RNG.choice(NBITS, size=n, replace=False)


def to_naive(words):
    return NaiveBitmap(unpack_positions(np.asarray(words)), NBITS)


DENSITIES = [0.0001, 0.01, 0.3, 0.9]  # array-like .. run-like densities


@pytest.mark.parametrize("da", DENSITIES)
@pytest.mark.parametrize("db", DENSITIES)
def test_binary_ops_match_oracle(da, db):
    pa, pb = random_positions(da), random_positions(db)
    a, b = pack_positions(pa, NBITS), pack_positions(pb, NBITS)
    na, nb = NaiveBitmap(pa, NBITS), NaiveBitmap(pb, NBITS)

    assert to_naive(b_and(a, b)).bits == na.intersect(nb).bits
    assert to_naive(b_or(a, b)).bits == na.union(nb).bits
    assert to_naive(b_xor(a, b)).bits == na.xor(nb).bits
    assert to_naive(b_andnot(a, b)).bits == na.difference(nb).bits
    assert int(popcount_and(a, b)) == na.intersect(nb).count()


def test_pack_unpack_roundtrip():
    for d in DENSITIES:
        p = np.sort(random_positions(d))
        words = pack_positions(p, NBITS)
        assert np.array_equal(unpack_positions(words), p)
        assert int(popcount(words)) == len(p)


def test_pack_empty_and_bounds():
    assert pack_positions([], NBITS).sum() == 0
    with pytest.raises(ValueError):
        pack_positions([NBITS], NBITS)
    with pytest.raises(ValueError):
        pack_positions([-1], NBITS)


def test_not_within_existence():
    pa = random_positions(0.1)
    pe = np.union1d(pa, random_positions(0.2))
    a, e = pack_positions(pa, NBITS), pack_positions(pe, NBITS)
    na, ne = NaiveBitmap(pa, NBITS), NaiveBitmap(pe, NBITS)
    assert to_naive(b_not(a, e)).bits == na.complement_within(ne).bits


@pytest.mark.parametrize("n", [1, 31, 32, 33, 64, 100, 65535])
def test_shift(n):
    pa = random_positions(0.05)
    a = pack_positions(pa, NBITS)
    na = NaiveBitmap(pa, NBITS)
    assert to_naive(b_shift(a, n)).bits == na.shift(n).bits


def test_shift_zero_identity():
    a = pack_positions(random_positions(0.05), NBITS)
    assert np.array_equal(np.asarray(b_shift(a, 0)), np.asarray(a))


@pytest.mark.parametrize(
    "start,end",
    [(0, NBITS), (0, 1), (31, 33), (100, 100), (5, 64), (NBITS - 1, NBITS), (7, 1000)],
)
def test_flip_range(start, end):
    pa = random_positions(0.1)
    a = pack_positions(pa, NBITS)
    na = NaiveBitmap(pa, NBITS)
    assert to_naive(b_flip_range(a, start, end)).bits == na.flip_range(start, end).bits


def test_set_clear_get_bits():
    a = pack_positions(random_positions(0.01), NBITS)
    oracle = to_naive(a)

    new_pos = RNG.choice(NBITS, size=50, replace=False)
    delta = pack_positions(new_pos, NBITS)
    idx = np.nonzero(delta)[0]
    a2 = set_bits(a, idx, delta[idx])
    assert to_naive(a2).bits == oracle.bits | set(int(p) for p in new_pos)

    a3 = clear_bits(a2, idx, delta[idx])
    assert to_naive(a3).bits == oracle.bits - set(int(p) for p in new_pos)

    probe = np.concatenate([new_pos[:10], RNG.choice(NBITS, size=10)])
    got = np.asarray(get_bits(a2, probe))
    want = np.array([1 if int(p) in to_naive(a2).bits else 0 for p in probe])
    assert np.array_equal(got, want)


def test_row_matrix_ops():
    rows = [1, 5, 9]
    pairs = []
    per_row = {}
    for r in rows:
        ps = random_positions(0.02)
        per_row[r] = NaiveBitmap(ps, NBITS)
        pairs += [(r, int(c)) for c in ps]
    mat = pack_positions_matrix(pairs, rows, NBITS)

    counts = np.asarray(row_counts(mat))
    assert [int(c) for c in counts] == [per_row[r].count() for r in rows]

    filt_pos = random_positions(0.1)
    filt = pack_positions(filt_pos, NBITS)
    nfilt = NaiveBitmap(filt_pos, NBITS)
    mcounts = np.asarray(row_counts_masked(mat, filt))
    assert [int(c) for c in mcounts] == [
        per_row[r].intersect(nfilt).count() for r in rows
    ]

    union = to_naive(reduce_or_rows(mat))
    want_u = set()
    for r in rows:
        want_u |= per_row[r].bits
    assert union.bits == want_u

    inter = to_naive(b_and(reduce_and_rows(mat), pack_positions(range(NBITS), NBITS)))
    want_i = per_row[rows[0]].bits
    for r in rows[1:]:
        want_i &= per_row[r].bits
    assert inter.bits == want_i


def test_word_layout_matches_uint64_view():
    """The uint32 device layout must reinterpret as the reference's uint64
    LSB-first word layout (roaring bitmap containers) byte-for-byte."""
    pos = [0, 1, 31, 32, 63, 64, 65, 127, NBITS - 1]
    words32 = pack_positions(pos, NBITS)
    words64 = words32.view(np.uint64)
    want = np.zeros(NBITS // 64, dtype=np.uint64)
    for p in pos:
        want[p // 64] |= np.uint64(1) << np.uint64(p % 64)
    assert np.array_equal(words64, want)


def test_n_words_validation():
    assert n_words(64) == 2
    with pytest.raises(ValueError):
        n_words(65)


def test_shift_past_width_is_empty_without_padding():
    """A shift >= the bitmap width returns zeros directly — no O(n)
    padded intermediate, no per-n compile."""
    import jax.numpy as jnp

    from pilosa_tpu.ops import bitmap as bm

    a = jnp.full((4, 8), 0xFFFFFFFF, dtype=jnp.uint32)
    for n in (8 * 32, 8 * 32 + 1, 10**9):
        out = bm.b_shift(a, n)
        assert out.shape == a.shape
        assert int(jnp.sum(out)) == 0
    # one word below the edge still shifts normally
    out = bm.b_shift(a, 8 * 32 - 32)
    assert int(out[0, -1]) == 0xFFFFFFFF and int(out[0, 0]) == 0


def test_negative_shift_raises_cleanly():
    import jax.numpy as jnp
    import pytest as _pytest

    from pilosa_tpu.ops import bitmap as bm

    a = jnp.zeros((2, 8), dtype=jnp.uint32)
    with _pytest.raises(ValueError, match="non-negative"):
        bm.b_shift(a, -1)
