"""Test configuration.

Forces JAX onto a virtual 8-device CPU platform BEFORE jax is imported, so
multi-chip sharding tests (the analog of the reference's in-process
multi-node clusters, test/pilosa.go:343-399) run anywhere.  Also pins a
small shard width so fragments stay tiny, mirroring the reference's
SHARD_WIDTH build-tag CI matrix (.circleci/config.yml:52-56).
"""

import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"  # force: the image pre-sets JAX_PLATFORMS=axon
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("PILOSA_TPU_SHARD_WIDTH_EXP", "16")

# jax may already be imported by a pytest plugin (the image ships an axon TPU
# site hook), and JAX_PLATFORMS is captured at import time — so also override
# via jax.config, which takes effect any time before backend initialization.
# test_environment.py asserts the 8-device CPU platform stuck.
import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # jax < 0.5 has no jax_num_cpu_devices option; the
    # xla_force_host_platform_device_count flag above covers it there
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Tests are CPU-only by design, but a pinned platform is not enough when
# the axon relay PROCESS is dead: PJRT plugin discovery then hangs
# backend init outright (even JAX_PLATFORMS=cpu).  Deregister the axon
# factory so the whole suite cannot hang on a relay outage.
from pilosa_tpu.axon_guard import scrub_axon_backend  # noqa: E402

scrub_axon_backend()

import pytest  # noqa: E402


@pytest.fixture(autouse=True)
def _hermetic_residency_accounting():
    """Reset the process-wide residency manager after every test.

    Tests that don't close their holders leak accounting entries into
    the global manager; individually harmless, but the accumulated
    total eventually trips budget gates in later tests (first seen:
    prewarm declining work at the shard-width-22 matrix leg, where
    stacks are 4x bigger).  Real servers close their holders on
    shutdown; per-test reset restores that hermeticity.  Orphaned cache
    entries stay functional (generation checks still validate) — they
    merely stop being tracked/evictable, which is fine for test
    lifetimes."""
    yield
    from pilosa_tpu.runtime import prewarm, residency

    # drain BEFORE reset: an in-flight background prewarm from the
    # finished test would otherwise admit into the next test's fresh
    # manager (the cross-test leak this fixture exists to stop, made
    # timing-dependent).  A timeout must fail HERE, pinned to the
    # offending test, not surface as a nondeterministic budget trip
    # three tests later.
    assert prewarm.drain(timeout=30), "prewarm drain timed out in teardown"
    residency.reset()
    # the query result cache is process-wide too; holder uids make
    # cross-test hits impossible, but a test that shrinks the budget
    # or disables it must not leak that config into the next test
    from pilosa_tpu.runtime import resultcache

    resultcache.reset()
    # streaming-ingest state is process-wide as well: a test that
    # enables delta planes (any in-process Server does) must not leak
    # delta semantics — or a running compactor thread — into the next
    # test's bare fragments
    from pilosa_tpu import ingest
    from pilosa_tpu.ingest import compactor

    ingest.reset()
    compactor.reset()
    # the [replication] write-policy / hint-queue config is
    # process-wide too: a test that flips write_policy="available"
    # must not leak degraded-write semantics into the next test
    from pilosa_tpu.parallel import hints

    hints.reset()
    # the [tenants] isolation policy is process-wide as well: a test
    # that enables quotas must not leak weighted-fair scheduling (or
    # per-tenant cache/residency accounting) into the next test
    from pilosa_tpu.serve import tenant

    tenant.reset()
