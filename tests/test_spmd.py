"""Collective (SPMD) query execution tests — the multi-host data plane
(VERDICT round-2 missing #2; reference scatter-gather analog
executor.go:2455, here replaced by global-mesh collectives).

Two tiers: a single-process tier on the 8-virtual-device CPU mesh
(parity of the collective evaluator against the product executor and a
Python-set oracle), and a REAL multi-process jax.distributed tier (2
and 3 processes) where full pilosa_tpu servers form an HTTP cluster,
fragments land by jump hash, and collective queries run with stacks
genuinely spanning every process's devices."""

from __future__ import annotations

import random

import pytest

from pilosa_tpu.models.field import FieldOptions
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.parallel import spmd
from pilosa_tpu.parallel.cluster import Cluster, Node
from pilosa_tpu.parallel.executor import Executor
from pilosa_tpu.parallel.results import Pair
from pilosa_tpu.shardwidth import SHARD_WIDTH


def _build(holder, n_shards=5, seed=11, cols_per_row=(300, 301),
           n_vals=400, val_range=(-500, 1 << 18)):
    idx = holder.create_index("i")
    f = idx.create_field("f")
    rng = random.Random(seed)
    bits: dict[int, set[int]] = {}
    rows_l, cols_l = [], []
    for row in range(4):
        cols = {rng.randrange(n_shards * SHARD_WIDTH)
                for _ in range(rng.randrange(*cols_per_row))}
        bits[row] = cols
        rows_l += [row] * len(cols)
        cols_l += list(cols)
    f.import_bits(rows_l, cols_l)
    v = idx.create_field("v", FieldOptions.int_field(*val_range))
    vcols = sorted({rng.randrange(n_shards * SHARD_WIDTH)
                    for _ in range(n_vals)})
    vals = {c: rng.randrange(*val_range) for c in vcols}
    v.import_values(vcols, [vals[c] for c in vcols])
    return idx, bits, vals


@pytest.fixture
def single(tmp_path):
    h = Holder(str(tmp_path / "h"))
    idx, bits, vals = _build(h)
    cluster = Cluster(local_id="n0")
    cluster.add_node(Node(id="n0", uri="local"))
    ce = spmd.CollectiveExecutor(h, cluster, "i")
    yield h, ce, Executor(h), bits, vals
    h.close()


class TestSingleProcessCollective:
    def test_count_tree_parity(self, single):
        h, ce, ex, bits, vals = single
        for pql, want in [
            ("Count(Row(f=0))", len(bits[0])),
            ("Count(Intersect(Row(f=0), Row(f=1)))",
             len(bits[0] & bits[1])),
            ("Count(Union(Row(f=0), Row(f=1), Row(f=2)))",
             len(bits[0] | bits[1] | bits[2])),
            ("Count(Difference(Row(f=0), Row(f=3)))",
             len(bits[0] - bits[3])),
            ("Count(Xor(Row(f=1), Row(f=2)))",
             len(bits[1] ^ bits[2])),
        ]:
            got = ce.execute(pql)
            assert got == want, (pql, got, want)
            assert got == ex.execute("i", pql)[0], pql

    def test_range_count_parity(self, single):
        h, ce, ex, bits, vals = single
        for pql, pred in [
            ("Count(Row(v > 100000))", lambda x: x > 100000),
            ("Count(Row(v <= 0))", lambda x: x <= 0),
            ("Count(Row(v == -5))", lambda x: x == -5),
            ("Count(Row(v >< [-100, 50000]))",
             lambda x: -100 <= x <= 50000),
            ("Count(Row(v != null))", lambda x: True),
        ]:
            want = sum(1 for x in vals.values() if pred(x))
            got = ce.execute(pql)
            assert got == want, (pql, got, want)
            assert got == ex.execute("i", pql)[0], pql

    def test_sum_parity(self, single):
        h, ce, ex, bits, vals = single
        got = ce.execute("Sum(field=v)")
        assert got.val == sum(vals.values())
        assert got.count == len(vals)
        assert got == ex.execute("i", "Sum(field=v)")[0]
        got = ce.execute("Sum(Row(f=1), field=v)")
        want = [v for c, v in vals.items() if c in bits[1]]
        assert got.val == sum(want) and got.count == len(want)
        assert got == ex.execute("i", "Sum(Row(f=1), field=v)")[0]

    def test_min_max_parity(self, single):
        h, ce, ex, bits, vals = single
        for pql in ("Min(field=v)", "Max(field=v)",
                    "Min(Row(f=1), field=v)", "Max(Row(f=1), field=v)"):
            got = ce.execute(pql)
            assert got == ex.execute("i", pql)[0], pql
        lo = min(vals.values())
        got = ce.execute("Min(field=v)")
        assert got.val == lo
        assert got.count == sum(1 for x in vals.values() if x == lo)
        hi = max(vals.values())
        got = ce.execute("Max(field=v)")
        assert got.val == hi
        assert got.count == sum(1 for x in vals.values() if x == hi)

    def test_topn_parity(self, single):
        h, ce, ex, bits, vals = single
        want = sorted(
            (Pair(id=r, count=len(c)) for r, c in bits.items() if c),
            key=lambda p: (-p.count, p.id))
        assert ce.execute("TopN(f)") == want
        assert ce.execute("TopN(f, n=2)") == want[:2]
        filt = ce.execute("TopN(f, Row(f=0), n=3)")
        wantf = sorted(
            ((r, len(c & bits[0])) for r, c in bits.items()),
            key=lambda rc: (-rc[1], rc[0]))
        wantf = [Pair(id=r, count=c) for r, c in wantf if c > 0][:3]
        assert filt == wantf
        assert filt == ex.execute("i", "TopN(f, Row(f=0), n=3)")[0]

    def test_not_shift_time_parity(self, single):
        h, ce, ex, bits, vals = single
        idx = h.index("i")
        # existence bits via the executor's write path (maintains _exists)
        for c in sorted(bits[0])[:50]:
            ex.execute("i", f"Set({c}, f=7)")
        for pql in ("Count(Not(Row(f=0)))",
                    "Count(Union(Row(f=1), Not(Row(f=2))))",
                    "Count(Shift(Row(f=0), n=3))",
                    "Count(Shift(Row(f=1)))"):
            got = ce.execute(pql)
            assert got == ex.execute("i", pql)[0], pql

        from pilosa_tpu.models.field import FieldOptions
        from pilosa_tpu.models.timequantum import parse_time

        t = idx.create_field("t", FieldOptions.time_field("YMD"))
        rng = random.Random(2)
        trows, tcols, times = [], [], []
        for _ in range(200):
            trows.append(4)
            tcols.append(rng.randrange(3 * SHARD_WIDTH))
            times.append(parse_time(
                f"2019-0{1 + rng.randrange(9)}-{1 + rng.randrange(27):02d}T00:00"))
        t.import_bits(trows, tcols, timestamps=times)
        for pql in (
            "Count(Row(t=4, from='2019-02-01T00:00', to='2019-05-01T00:00'))",
            "Count(Row(t=4, from='2019-01-01T00:00', to='2020-01-01T00:00'))",
            "Count(Intersect(Row(f=0), Row(t=4, from='2019-01-01T00:00', "
            "to='2019-07-01T00:00')))",
        ):
            got = ce.execute(pql)
            assert got == ex.execute("i", pql)[0], pql
        # open-ended ranges need the local clamp: scatter path only
        with pytest.raises(spmd.CollectiveError):
            ce.execute("Count(Row(t=4, from='2019-01-01T00:00'))")

    def test_open_time_range_resolution(self, single):
        """Coordinator-side rewrite of open-ended time bounds to the
        GLOBAL view clamp (the collective analog of the scatter path's
        per-node _clamp_to_views): detection, peer-bounds merge, text
        round-trip, and the no-views-anywhere empty rewrite."""
        h, ce, ex, bits, vals = single
        idx = h.index("i")

        from pilosa_tpu.models.timequantum import parse_time
        from pilosa_tpu.pql import parse

        t = idx.create_field("t", FieldOptions.time_field("YMD"))
        rng = random.Random(5)
        trows, tcols, times = [], [], []
        for _ in range(120):
            trows.append(1)
            tcols.append(rng.randrange(3 * SHARD_WIDTH))
            times.append(parse_time(
                f"2019-0{1 + rng.randrange(9)}-"
                f"{1 + rng.randrange(27):02d}T00:00"))
        t.import_bits(trows, tcols, timestamps=times)

        call = parse("Count(Row(t=1, from='2019-03-01T00:00'))").calls[0]
        assert spmd._open_time_fields(idx, call) == {"t"}
        # bounded, non-time, and condition rows never trigger a round
        for pql in ("Count(Row(t=1, from='2019-01-01T00:00', "
                    "to='2019-02-01T00:00'))",
                    "Count(Row(f=0))", "Count(Row(v > 10))"):
            assert spmd._open_time_fields(idx, parse(pql).calls[0]) == set()

        class _N:
            def __init__(self, id):
                self.id = id

        sent = []

        class _Transport:
            def send_message(self, n, msg):
                sent.append((n.id, msg))
                return {"ok": True, "bounds":
                        {"t": ["2018-06-01T00:00", "2020-02-01T00:00"]}}

        class _Cluster:
            local_id = "n0"
            transport = _Transport()

            def sorted_nodes(self):
                return [_N("n0"), _N("n1")]

        class _Node:
            cluster = _Cluster()

        out = spmd._resolve_open_time_ranges(_Node(), idx, "i", call)
        row = out.children[0]
        assert row.args["from"] == "2019-03-01T00:00"  # given: untouched
        # peer's later bound wins the merge; +366d widening like
        # executor._clamp_to_views
        assert row.args["to"] == "2021-02-01T00:00"
        assert sent and sent[0][1]["type"] == "collective-time-bounds"
        # the rewritten call round-trips through PQL text (what ships)
        assert str(parse(str(out)).calls[0]) == str(out)
        # ... and the bounded rewrite is now collectively evaluable,
        # matching the executor's open-ended evaluation exactly
        want = ex.execute("i", "Count(Row(t=1, from='2019-03-01T00:00'))")[0]
        assert ce.execute(f"Count({row})") == want

        # no views anywhere: rewrite to a concrete empty range
        class _TransportNone:
            def send_message(self, n, msg):
                return {"ok": True, "bounds": {"u": None}}

        idx.create_field("u", FieldOptions.time_field("YMD"))
        _Node.cluster.transport = _TransportNone()
        call2 = parse("Count(Row(u=1, to='2019-01-01T00:00'))").calls[0]
        out2 = spmd._resolve_open_time_ranges(_Node(), idx, "i", call2)
        r2 = out2.children[0]
        assert r2.args["from"] == r2.args["to"] == spmd._EMPTY_RANGE_TS
        assert ce.execute(f"Count({r2})") == 0

        # a peer that cannot answer aborts resolution (scatter fallback)
        class _TransportErr:
            def send_message(self, n, msg):
                return {"ok": False, "error": "nope"}

        _Node.cluster.transport = _TransportErr()
        with pytest.raises(spmd.CollectiveError):
            spmd._resolve_open_time_ranges(
                _Node(), idx, "i",
                parse("Count(Row(t=1, from='2019-03-01T00:00'))").calls[0])

    def test_time_bounds_bus_message(self, tmp_path):
        """Peer side of the resolution round: the collective-time-bounds
        bus message reports the local view span per field."""
        from pilosa_tpu.models.timequantum import parse_time
        from pilosa_tpu.parallel.node import ClusterNode

        h = Holder(str(tmp_path / "hb"))
        idx = h.create_index("i")
        t = idx.create_field("t", FieldOptions.time_field("YM"))
        t.import_bits([0, 0], [5, 9],
                      timestamps=[parse_time("2020-03-15T00:00"),
                                  parse_time("2020-11-02T00:00")])
        idx.create_field("empty_t", FieldOptions.time_field("YMD"))
        cluster = Cluster(local_id="n0")
        cluster.add_node(Node(id="n0", uri="local"))
        node = ClusterNode(h, cluster)
        r = node.receive_message(
            {"type": "collective-time-bounds", "index": "i",
             "fields": ["t", "empty_t", "missing"]})
        assert r["ok"]
        # YM quantum: the year view floors the min to the year start;
        # the latest month view sets the max
        assert r["bounds"]["t"] == ["2020-01-01T00:00", "2020-11-01T00:00"]
        assert r["bounds"]["empty_t"] is None
        assert r["bounds"]["missing"] is None
        r = node.receive_message(
            {"type": "collective-time-bounds", "index": "nope",
             "fields": ["t"]})
        assert not r["ok"]
        h.close()

    def test_group_by_parity(self, single):
        h, ce, ex, bits, vals = single
        # second field so the 2-child walk crosses field boundaries
        g = h.index("i").create_field("g")
        rows_l, cols_l = [], []
        for row in range(3):
            for c in sorted(bits[row])[: 120]:
                rows_l.append(row)
                cols_l.append(c)
        g.import_bits(rows_l, cols_l)
        for pql in ("GroupBy(Rows(f))",
                    "GroupBy(Rows(f), Rows(g))",
                    "GroupBy(Rows(f), Rows(g), filter=Row(f=0))",
                    "GroupBy(Rows(f), Rows(g), limit=3)",
                    "GroupBy(Rows(f), Rows(g), offset=2, limit=4)",
                    # 3-level nests: lockstep outer loop (round 3)
                    "GroupBy(Rows(f), Rows(g), Rows(f))",
                    "GroupBy(Rows(f), Rows(g), Rows(g), "
                    "filter=Row(f=1))",
                    "GroupBy(Rows(g), Rows(f), Rows(g), offset=3, "
                    "limit=5)",
                    "GroupBy(Rows(f, limit=2), Rows(g), "
                    "Rows(f, previous=0))"):
            got = ce.execute(pql)
            want = ex.execute("i", pql)[0]
            assert got == want, (pql, got, want)

    def test_unsupported_calls_refused(self, single):
        h, ce, ex, bits, vals = single
        for pql in ("Set(5, f=1)",  # writes never run collectively
                    "GroupBy(Rows(f), previous=1)",
                    "Count(Row(f=0, from='2019-01-01T00:00'))",
                    # bare open-ended time Row: needs the coordinator's
                    # bounds resolution, declined at the evaluator
                    "Row(f=0, from='2019-01-01T00:00')",
                    # attrName without a list attrValues is the scatter
                    # path's user error; malformed tanimoto likewise
                    'TopN(f, attrName="x")',
                    "TopN(f, Row(f=0), tanimotoThreshold=101)"):
            with pytest.raises(spmd.CollectiveError):
                ce.execute(pql)

    def test_bare_bitmap_parity(self, single):
        """Bare bitmap trees — the single most ordinary PQL read —
        return a global Row assembled from the replicated gather,
        exactly matching the scatter executor (round-4 VERDICT #3;
        reference executeBitmapCall, executor.go:651)."""
        h, ce, ex, bits, vals = single
        for pql in ("Row(f=0)",
                    "Union(Row(f=0), Row(f=1), Row(f=2))",
                    "Intersect(Row(f=0), Row(f=1))",
                    "Difference(Row(f=2), Row(f=3))",
                    "Xor(Row(f=1), Row(f=2))",
                    "Shift(Row(f=0), n=5)",
                    "Row(v > 100000)",
                    "Row(v >< [-100, 50000])"):
            got = ce.execute(pql)
            want = ex.execute("i", pql)[0]
            assert got == want, (pql, len(got.columns()),
                                 len(want.columns()))
        # oracle spot-checks (not just plane agreement)
        got = ce.execute("Union(Row(f=0), Row(f=1))")
        assert sorted(int(c) for c in got.columns()) == \
            sorted(bits[0] | bits[1])
        got = ce.execute("Row(v > 100000)")
        assert sorted(int(c) for c in got.columns()) == \
            sorted(c for c, x in vals.items() if x > 100000)

    def test_bare_bitmap_windowed_gather(self, single, monkeypatch):
        """Past MAX_ROW_GATHER_BYTES the bare-bitmap result replicates
        in shard-range windows instead of one all-gather — same exact
        Row, bounded per-process transient (round-5 VERDICT #8).
        Shrinking the bound to ~2 shards per window forces the 5-shard
        index through the windowed path, including the clamped
        overlapping last window."""
        h, ce, ex, bits, vals = single
        words = spmd.bm.n_words(SHARD_WIDTH)
        for max_shards in (1, 2, 3):
            monkeypatch.setattr(spmd, "MAX_ROW_GATHER_BYTES",
                                max_shards * words * 4)
            for pql in ("Row(f=0)",
                        "Union(Row(f=0), Row(f=1), Row(f=2))",
                        "Row(v > 100000)"):
                got = ce.execute(pql)
                want = ex.execute("i", pql)[0]
                assert got == want, (max_shards, pql)
        got = ce.execute("Union(Row(f=0), Row(f=1))")
        assert sorted(int(c) for c in got.columns()) == \
            sorted(bits[0] | bits[1])

    def test_wide_group_by_parity(self, single):
        """4+-child GroupBy runs collectively via the outer cartesian
        lockstep loop (round-4 VERDICT #3)."""
        h, ce, ex, bits, vals = single
        g = h.index("i").field("g")
        if g is None:
            g = h.index("i").create_field("g")
            rows_l, cols_l = [], []
            for row in range(3):
                for c in sorted(bits[row])[:150]:
                    rows_l.append(row)
                    cols_l.append(c)
            g.import_bits(rows_l, cols_l)
        for pql in ("GroupBy(Rows(f), Rows(g), Rows(f), Rows(g))",
                    "GroupBy(Rows(f), Rows(g), Rows(f), Rows(g), "
                    "filter=Row(f=0))",
                    "GroupBy(Rows(f, limit=2), Rows(g), Rows(f), "
                    "Rows(g), limit=30, offset=4)",
                    "GroupBy(Rows(g), Rows(g), Rows(f), Rows(g), "
                    "Rows(f))"):
            got = ce.execute(pql)
            want = ex.execute("i", pql)[0]
            assert got == want, (pql, got[:4], want[:4])

    def test_group_by_constrained_children_parity(self, single):
        """Rows-child limit/column/previous constraints match the
        executor: column resolves via one collective bit gather, then
        previous/limit apply to the agreed list (the executor's
        _execute_rows order)."""
        h, ce, ex, bits, vals = single
        # a column present in row 1 and row 3 (deterministic probe)
        col13 = next(iter(bits[1] & bits[3]
                          or bits[1]))  # overlap or fall back to row 1
        for pql in ("GroupBy(Rows(f, limit=2))",
                    "GroupBy(Rows(f, previous=0))",
                    "GroupBy(Rows(f, previous=1, limit=1))",
                    f"GroupBy(Rows(f, column={col13}))",
                    f"GroupBy(Rows(f, column={col13}, limit=1))",
                    f"GroupBy(Rows(f, column={col13}), Rows(f))",
                    "GroupBy(Rows(f, limit=3), Rows(f, previous=0), "
                    "filter=Row(f=2))",
                    "GroupBy(Rows(f, column=999999999))"):  # absent col
            got = ce.execute(pql)
            want = ex.execute("i", pql)[0]
            assert got == want, (pql, got, want)

    def test_group_by_time_children_parity(self, single):
        """Time-constrained GroupBy Rows children match the scatter
        path's reference-faithful semantics (executor.go:1104-1117 +
        newGroupByIterator executor.go:3102): from/to bites only
        through the constrained-child row pre-selection; counts always
        come from the standard view; a no-standard-view child empties
        the whole GroupBy."""
        import datetime as dt

        from pilosa_tpu.models.field import FieldOptions as FO

        h, ce, ex, bits, vals = single
        idx = h.index("i")
        t = idx.create_field("t", FO.time_field("YMD"))
        ns = idx.create_field("ns", FO.time_field(
            "YMD", no_standard_view=True))
        rng = random.Random(55)
        for fld in (t, ns):
            rows_l, cols_l, ts_l = [], [], []
            for row in range(3):
                for c in sorted(bits[row])[:80]:
                    rows_l.append(row)
                    cols_l.append(c)
                    ts_l.append(dt.datetime(2020, rng.randrange(1, 13),
                                            rng.randrange(1, 28)))
            fld.import_bits(rows_l, cols_l, ts_l)
        for pql in (
                # unconstrained: from/to ignored (reference semantics)
                "GroupBy(Rows(t, from='2020-03-01T00:00', "
                "to='2020-06-01T00:00'))",
                # constrained: selection honors the time cover
                "GroupBy(Rows(t, from='2020-03-01T00:00', "
                "to='2020-06-01T00:00', limit=2))",
                "GroupBy(Rows(t, from='2020-02-01T00:00', "
                "to='2020-11-01T00:00', previous=0), Rows(f))",
                "GroupBy(Rows(f), Rows(t, from='2020-01-01T00:00', "
                "to='2021-01-01T00:00', limit=2), Rows(f))",
                # no-standard-view children: constant empty
                "GroupBy(Rows(ns))",
                "GroupBy(Rows(ns, limit=3))",
                "GroupBy(Rows(ns), Rows(f))"):
            got = ce.execute(pql)
            want = ex.execute("i", pql)[0]
            assert got == want, (pql, got, want)

    def test_options_parity(self, single):
        """Options() runs collectively: shards restrict the plan (and
        the agreed row lists), serialization flags ride the result —
        matching the scatter executor (reference executeOptionsCall)."""
        h, ce, ex, bits, vals = single
        for pql in ("Options(Count(Row(f=0)), shards=[0, 2])",
                    "Options(Count(Union(Row(f=0), Row(f=1))), "
                    "shards=[1])",
                    "Options(Row(f=1), excludeColumns=true)",
                    "Options(Sum(Row(f=0), field=v), shards=[0, 1, 3])",
                    "Options(TopN(f), shards=[2])",
                    "Options(Rows(f), shards=[0])",
                    "Options(Count(Row(f=2)), shards=[])"):
            got = ce.execute(pql)
            want = ex.execute("i", pql)[0]
            assert got == want, (pql, got, want)
        # flags ride the Row result like the scatter plane's
        r = ce.execute("Options(Row(f=0), excludeColumns=true)")
        assert r.exclude_columns is True
        r = ce.execute("Options(Row(f=0), columnAttrs=true)")
        assert r.wants_column_attrs is True
        # nested Options: inner levels override (scatter recurses too)
        got = ce.execute("Options(Options(Count(Row(f=0)), shards=[0]), "
                         "shards=[0, 1, 2, 3, 4])")
        want = ex.execute(
            "i", "Options(Options(Count(Row(f=0)), shards=[0]), "
            "shards=[0, 1, 2, 3, 4])")[0]
        assert got == want
        # unknown options stay the scatter path's user error; writes
        # under Options never run collectively
        with pytest.raises(spmd.CollectiveError):
            ce.execute("Options(Count(Row(f=0)), bogus=true)")
        with pytest.raises(spmd.CollectiveError):
            ce.execute("Options(Set(9999, f=0), shards=[0])")

    def test_rows_and_extreme_row_parity(self, single):
        """Standalone Rows (incl. constraints and time covers) and
        MinRow/MaxRow run collectively, matching the scatter executor
        (round 4: the ordinary-read surface rounds out)."""
        import datetime as dt

        from pilosa_tpu.models.field import FieldOptions as FO

        h, ce, ex, bits, vals = single
        idx = h.index("i")
        t = idx.create_field("t2", FO.time_field("YMD"))
        rng = random.Random(77)
        rows_l, cols_l, ts_l = [], [], []
        for row in range(4):
            for c in sorted(bits[row])[:60]:
                rows_l.append(row)
                cols_l.append(c)
                ts_l.append(dt.datetime(2021, rng.randrange(1, 13), 5))
        t.import_bits(rows_l, cols_l, ts_l)
        col0 = min(bits[0])
        for pql in ("Rows(f)",
                    "Rows(f, limit=2)",
                    "Rows(f, previous=1)",
                    f"Rows(f, column={col0})",
                    # time field: from/to select the covering views
                    "Rows(t2)",
                    "Rows(t2, from='2021-01-01T00:00', "
                    "to='2021-06-01T00:00')",
                    "Rows(t2, from='2021-01-01T00:00', "
                    "to='2022-01-01T00:00', limit=2)",
                    "MinRow(field=f)",
                    "MaxRow(field=f)",
                    "MinRow(Row(f=1), field=f)",
                    "MaxRow(Row(f=0), field=f)"):
            got = ce.execute(pql)
            want = ex.execute("i", pql)[0]
            assert got == want, (pql, got, want)

    def test_topn_arg_parity(self, single):
        """threshold/ids/tanimoto TopN args match the executor exactly
        (post-count filters on the complete global counts)."""
        h, ce, ex, bits, vals = single
        for pql in ("TopN(f, n=2, threshold=100)",
                    "TopN(f, threshold=301)",
                    "TopN(f, ids=[0,2])",
                    "TopN(f, ids=[1], n=1)",
                    "TopN(f, Row(f=1), ids=[0,1,3])",
                    "TopN(f, Row(f=0), threshold=10)",
                    "TopN(f, Row(f=1), tanimotoThreshold=30)",
                    "TopN(f, Row(f=0), tanimotoThreshold=95)",
                    "TopN(f, tanimotoThreshold=50)"):  # no filter: inert
            got = ce.execute(pql)
            want = ex.execute("i", pql)[0]
            assert [(p.id, p.count) for p in got] == \
                   [(p.id, p.count) for p in want], pql

    def test_topn_attr_filter_parity(self, single):
        """attrName/attrValues filter host-side on the complete global
        counts, matching the executor (the device programs are
        unchanged, so SPMD lockstep holds)."""
        h, ce, ex, bits, vals = single
        f = h.index("i").field("f")
        f.row_attrs.set_attrs(0, {"color": "red", "size": 3})
        f.row_attrs.set_attrs(1, {"color": "blue"})
        f.row_attrs.set_attrs(2, {"color": "red"})
        for pql in ('TopN(f, attrName="color", attrValues=["red"])',
                    'TopN(f, attrName="color", attrValues=["blue"], n=1)',
                    'TopN(f, attrName="size", attrValues=[3])',
                    'TopN(f, attrName="color", attrValues=["green"])',
                    'TopN(f, Row(f=1), attrName="color", '
                    'attrValues=["red","blue"])',
                    'TopN(f, attrName="color", attrValues=["red"], '
                    'threshold=100)'):
            got = ce.execute(pql)
            want = ex.execute("i", pql)[0]
            assert [(p.id, p.count) for p in got] == \
                   [(p.id, p.count) for p in want], pql

    def test_fuzz_sentinel_folding(self, tmp_path, monkeypatch):
        """Randomized keyed trees mixing real and MISSING keys through
        the coordinator: whenever try_collective answers, it must match
        the executor (which handles sentinels natively) and a Python
        oracle — and the fold must actually engage on a healthy
        fraction of ghost-bearing trees."""
        from pilosa_tpu.parallel.node import ClusterNode

        h = Holder(str(tmp_path / "h"))
        cluster = Cluster(local_id="n0")
        cluster.add_node(Node(id="n0", uri="local"))
        cluster.coordinator_id = "n0"
        cluster.set_state("NORMAL")
        node = ClusterNode(h, cluster)
        idx = h.create_index("i")
        idx.create_field("kf", FieldOptions.set_field(keys=True))
        rng = random.Random(2718)
        real = {}
        for key in ("a", "b", "c", "d"):
            cols = {rng.randrange(3000) for _ in range(200)}
            real[key] = cols
        # bulk-load via the executor write path (keys allocate ids)
        for key, cols in real.items():
            for c in sorted(cols):
                node.executor.execute("i", f'Set({c}, kf="{key}")')
        ghosts = ["g1", "g2"]

        def gen(depth):
            if depth == 0 or rng.random() < 0.4:
                key = rng.choice(list(real) + ghosts)
                return f'Row(kf="{key}")', real.get(key, set())
            op = rng.choice(["Union", "Intersect", "Difference", "Xor"])
            n = rng.randrange(2, 4)
            parts = [gen(depth - 1) for _ in range(n)]
            texts = [p[0] for p in parts]
            sets = [p[1] for p in parts]
            if op == "Union":
                acc = set().union(*sets)
            elif op == "Intersect":
                acc = sets[0]
                for s in sets[1:]:
                    acc = acc & s
            elif op == "Difference":
                acc = sets[0]
                for s in sets[1:]:
                    acc = acc - s
            else:
                acc = sets[0]
                for s in sets[1:]:
                    acc = acc ^ s
            return f"{op}({', '.join(texts)})", acc

        monkeypatch.setattr(spmd, "collective_available", lambda: True)
        answered_with_ghost = 0
        try:
            for _ in range(100):
                text, oracle = gen(depth=2)
                q = f"Count({text})"
                want = node.executor.execute("i", q)[0]
                assert want == len(oracle), (q, want, len(oracle))
                res = spmd.try_collective(node, "i", q)
                if res is not None:
                    assert res == [want], (q, res, want)
                    if '"g' in text:
                        answered_with_ghost += 1
            # the fold must be doing real work, not refusing everything
            assert answered_with_ghost >= 10, answered_with_ghost
        finally:
            h.close()

    def test_untranslated_key_args_refused(self, single):
        """The evaluator is id-space only: STRING row args (keys that
        never went through the coordinator's translation) are refused —
        the translated forms are covered by the keyed-query test."""
        h, ce, ex, bits, vals = single
        h.index("i").create_field(
            "kf", FieldOptions.set_field(keys=True))
        for pql in ('Count(Row(kf="alice"))',
                    'Count(Intersect(Row(f=0), Row(kf="x")))',
                    'Count(Row(f="stringy"))'):
            with pytest.raises(spmd.CollectiveError):
                ce.execute(pql)

    def test_fuzz_collective_vs_scatter_vs_oracle(self, tmp_path):
        """Randomized differential sweep: every collective-supported
        query shape must agree with BOTH the product executor and a
        Python-set oracle — the three-way check that caught the resize
        cache bug, applied to the whole collective surface."""
        import contextlib

        with contextlib.closing(Holder(str(tmp_path / "h"))) as h:
            self._run_fuzz(h)

    def _run_fuzz(self, h):
        from pilosa_tpu.pql import parse_python
        from tests.test_fuzz_stress import eval_set_algebra, gen_query

        idx = h.create_index("i")
        rng = random.Random(777)
        n_shards = 4
        row_sets: dict[tuple[str, int], set] = {}
        universe: set[int] = set()
        for fi in range(3):
            f = idx.create_field(f"f{fi}")
            rows_l, cols_l = [], []
            for row in range(5):
                cols = {rng.randrange(n_shards * SHARD_WIDTH)
                        for _ in range(rng.randrange(50, 250))}
                row_sets[(f"f{fi}", row)] = cols
                rows_l += [row] * len(cols)
                cols_l += list(cols)
                universe |= cols
            f.import_bits(rows_l, cols_l)
        # existence rows for Not: both planes complement against _exists
        ex = Executor(h)
        idx.existence_field().import_bits([0] * len(universe),
                                          sorted(universe))

        cluster = Cluster(local_id="n0")
        cluster.add_node(Node(id="n0", uri="local"))
        ce = spmd.CollectiveExecutor(h, cluster, "i")
        checked = 0
        for _ in range(120):
            q = f"Count({gen_query(rng, depth=1)})"
            calls = parse_python(q).calls
            if not ce.supported(calls[0]):
                continue
            want = len(eval_set_algebra(calls[0].children[0],
                                        row_sets, universe))
            got_c = ce.execute(q)
            got_x = ex.execute("i", q)[0]
            assert got_c == want == got_x, (q, got_c, got_x, want)
            checked += 1
        assert checked >= 60, f"only {checked} shapes exercised"

    def test_fuzz_aggregates_and_conditions(self, tmp_path):
        """Randomized aggregate surface: Sum/Min/Max with random filter
        trees, BSI-condition counts with random ops/predicates, TopN
        and GroupBy with random filters — collective vs executor vs
        dict/set oracles."""
        import contextlib

        with contextlib.closing(Holder(str(tmp_path / "h"))) as h:
            self._run_agg_fuzz(h)

    def _run_agg_fuzz(self, h):
        rng = random.Random(4040)
        idx, bits, vals = _build(h, n_shards=3, seed=4040,
                                 cols_per_row=(80, 300), n_vals=500,
                                 val_range=(-3000, 90000))
        # densify the row/value overlap: uniform draws over the column
        # space make filtered aggregates almost always empty (the fuzz
        # would rubber-stamp (0,0)==(0,0)); giving ~60% of each row's
        # columns a BSI value makes every filter branch non-trivial
        overlap = sorted({c for cols in bits.values()
                          for c in rng.sample(sorted(cols),
                                              int(len(cols) * 0.6))})
        v = idx.field("v")
        new_vals = {c: rng.randrange(-3000, 90000) for c in overlap}
        v.import_values(list(new_vals), list(new_vals.values()))
        vals.update(new_vals)
        assert any(vals.keys() & cols for cols in bits.values())
        cluster = Cluster(local_id="n0")
        cluster.add_node(Node(id="n0", uri="local"))
        ce = spmd.CollectiveExecutor(h, cluster, "i")
        ex = Executor(h)
        import operator as op

        cmps = {"<": op.lt, "<=": op.le, ">": op.gt, ">=": op.ge,
                "==": op.eq, "!=": op.ne}
        for i in range(80):
            kind = rng.randrange(5)
            if kind == 0:  # BSI condition count
                o = rng.choice(list(cmps))
                p = rng.randrange(-4000, 95000)
                q = f"Count(Row(v {o} {p}))"
                want = sum(1 for x in vals.values() if cmps[o](x, p))
                assert ce.execute(q) == want == ex.execute("i", q)[0], q
            elif kind == 1:  # between
                a = rng.randrange(-4000, 95000)
                b = a + rng.randrange(0, 50000)
                q = f"Count(Row(v >< [{a}, {b}]))"
                want = sum(1 for x in vals.values() if a <= x <= b)
                assert ce.execute(q) == want == ex.execute("i", q)[0], q
            elif kind == 2:  # Sum with random row filter
                r = rng.randrange(4)
                q = f"Sum(Row(f={r}), field=v)"
                sel = [x for c, x in vals.items() if c in bits[r]]
                got = ce.execute(q)
                assert (got.val, got.count) == (sum(sel), len(sel)), q
                assert got == ex.execute("i", q)[0], q
            elif kind == 3:  # Min/Max with random filter
                name = rng.choice(["Min", "Max"])
                r = rng.randrange(4)
                q = f"{name}(Row(f={r}), field=v)"
                got = ce.execute(q)
                assert got == ex.execute("i", q)[0], q
                sel = [x for c, x in vals.items() if c in bits[r]]
                if sel:
                    best = min(sel) if name == "Min" else max(sel)
                    assert (got.val, got.count) == \
                        (best, sel.count(best)), q
            else:  # TopN / GroupBy with random filter
                r = rng.randrange(4)
                roll = rng.random()
                if roll < 0.35:
                    q = f"TopN(f, Row(f={r}), n=3)"
                    got = ce.execute(q)
                    want = sorted(((rid, len(c & bits[r]))
                                   for rid, c in bits.items()),
                                  key=lambda rc: (-rc[1], rc[0]))
                    want = [(rid, c) for rid, c in want if c > 0][:3]
                    assert [(p.id, p.count) for p in got] == want, q
                elif roll < 0.6:
                    # random post-count arg mix: executor is the oracle
                    arg = rng.choice([
                        f"threshold={rng.randrange(1, 250)}",
                        f"ids=[{r}, {(r + 1) % 4}]",
                        f"tanimotoThreshold={rng.randrange(5, 99)}"])
                    q = f"TopN(f, Row(f={r}), n=3, {arg})"
                    got = ce.execute(q)
                else:
                    q = f"GroupBy(Rows(f), filter=Row(f={r}))"
                    got = ce.execute(q)
                    want = {rid: len(c & bits[r])
                            for rid, c in bits.items()
                            if len(c & bits[r])}
                    assert {g.group[0].row_id: g.count
                            for g in got} == want, q
                assert got == ex.execute("i", q)[0], q

    def test_keyed_queries_translate_then_run_collectively(
            self, tmp_path, monkeypatch):
        """try_collective translates string keys to ids ONCE at the
        origin (executor.go:146 semantics), ships id-only text, and
        re-keys the result; missing keys produce sentinel trees that
        fall back to the scatter path."""
        from pilosa_tpu.parallel.node import ClusterNode

        h = Holder(str(tmp_path / "h"))
        cluster = Cluster(local_id="n0")
        cluster.add_node(Node(id="n0", uri="local"))
        cluster.coordinator_id = "n0"
        cluster.set_state("NORMAL")
        node = ClusterNode(h, cluster)
        idx = h.create_index("i")
        idx.create_field("kf", FieldOptions.set_field(keys=True))
        for col, key in [(1, "alice"), (2, "alice"), (3, "bob"),
                         (2, "bob"), (9, "carol")]:
            node.executor.execute("i", f'Set({col}, kf="{key}")')

        monkeypatch.setattr(spmd, "collective_available", lambda: True)
        try:
            res = spmd.try_collective(node, "i",
                                      'Count(Row(kf="alice"))')
            assert res == [2], res
            assert spmd.try_collective(node, "i", 'TopN(kf)') is not None
            pairs = spmd.try_collective(node, "i", "TopN(kf)")[0]
            assert [(p.key, p.count) for p in pairs] == \
                [("alice", 2), ("bob", 2), ("carol", 1)]
            # missing key -> sentinel tree -> scatter path (None)
            assert spmd.try_collective(
                node, "i", 'Count(Row(kf="ghost"))') is None
            # and the scatter path answers it with the proper semantics
            assert node.executor.execute(
                "i", 'Count(Row(kf="ghost"))')[0] == 0
        finally:
            h.close()

    def test_sentinel_folding(self, tmp_path, monkeypatch):
        """Missing read keys fold out of the tree by set algebra at the
        coordinator (Union drops the empty child, Difference keeps its
        head, ...) so mixed trees still run collectively; only
        unfoldable shapes — whole-tree empty, Not(empty) — fall back
        to the scatter path (reference: missing keys are empty rows,
        executor.go:2610)."""
        from pilosa_tpu.parallel.node import ClusterNode
        from pilosa_tpu.pql import Call

        h = Holder(str(tmp_path / "h"))
        cluster = Cluster(local_id="n0")
        cluster.add_node(Node(id="n0", uri="local"))
        cluster.coordinator_id = "n0"
        cluster.set_state("NORMAL")
        node = ClusterNode(h, cluster)
        idx = h.create_index("i")
        idx.create_field("kf", FieldOptions.set_field(keys=True))
        for col, key in [(1, "alice"), (2, "alice"), (3, "bob"),
                         (2, "bob"), (9, "carol")]:
            node.executor.execute("i", f'Set({col}, kf="{key}")')

        monkeypatch.setattr(spmd, "collective_available", lambda: True)
        try:
            # Union: the empty child drops; answered collectively
            q = 'Count(Union(Row(kf="alice"), Row(kf="ghost")))'
            assert spmd.try_collective(node, "i", q) == [2]
            assert node.executor.execute("i", q)[0] == 2
            # Difference head survives
            q = 'Count(Difference(Row(kf="alice"), Row(kf="ghost")))'
            assert spmd.try_collective(node, "i", q) == [2]
            # Xor: empty is the identity
            q = 'Count(Xor(Row(kf="ghost"), Row(kf="bob")))'
            assert spmd.try_collective(node, "i", q) == [2]
            # Intersect with an empty leg folds to whole-tree empty:
            # scatter path answers (collective declines)
            q = 'Count(Intersect(Row(kf="alice"), Row(kf="ghost")))'
            assert spmd.try_collective(node, "i", q) is None
            assert node.executor.execute("i", q)[0] == 0
            # TopN filter tree folds too
            q = 'TopN(kf, Union(Row(kf="alice"), Row(kf="ghost")))'
            pairs = spmd.try_collective(node, "i", q)[0]
            assert [(p.key, p.count) for p in pairs] == \
                [("alice", 2), ("bob", 1)]
        finally:
            h.close()

        # algebra unit cases on raw trees
        E = Call("_Empty")
        row = Call("Row", {"f": 1})
        assert spmd._fold_bitmap_tree(Call("Not", children=[E])) is None
        assert spmd._fold_bitmap_tree(
            Call("Shift", {"n": 2}, [E])) is spmd._EMPTY_TREE
        assert spmd._fold_bitmap_tree(
            Call("Difference", children=[E, row])) is spmd._EMPTY_TREE
        u = spmd._fold_bitmap_tree(Call("Union", children=[E, row, E]))
        assert u is row
        x = spmd._fold_bitmap_tree(
            Call("Xor", children=[E, row, Call("Row", {"f": 2})]))
        assert x.name == "Xor" and len(x.children) == 2

    def test_row_attr_attachment_matches_scatter_plane(
            self, tmp_path, monkeypatch):
        """Row attrs attach for a LITERAL user Row() only — a tree that
        sentinel-folds down to a Row must serialize identically on both
        planes (the reference attaches only for Row calls,
        executor.go:206)."""
        from pilosa_tpu.parallel.node import ClusterNode

        h = Holder(str(tmp_path / "h"))
        cluster = Cluster(local_id="n0")
        cluster.add_node(Node(id="n0", uri="local"))
        cluster.coordinator_id = "n0"
        cluster.set_state("NORMAL")
        node = ClusterNode(h, cluster)
        idx = h.create_index("i")
        idx.create_field("kf", FieldOptions.set_field(keys=True))
        for col, key in [(1, "alice"), (2, "alice"), (3, "bob")]:
            node.executor.execute("i", f'Set({col}, kf="{key}")')
        node.executor.execute(
            "i", 'SetRowAttrs(kf, "alice", color="red")')
        monkeypatch.setattr(spmd, "collective_available", lambda: True)
        try:
            q = 'Row(kf="alice")'
            r_coll = spmd.try_collective(node, "i", q)[0]
            r_scat = node.executor.execute("i", q)[0]
            assert r_coll.attrs == r_scat.attrs == {"color": "red"}
            assert r_coll == r_scat
            # folded Union(Row, ghost) -> Row: neither plane attaches
            q = 'Union(Row(kf="alice"), Row(kf="ghost"))'
            u_coll = spmd.try_collective(node, "i", q)[0]
            u_scat = node.executor.execute("i", q)[0]
            assert u_coll == u_scat
            assert u_coll.attrs == u_scat.attrs == {}
        finally:
            h.close()

    def test_rank_convention_checker(self, single):
        h, ce, ex, bits, vals = single
        # single process: rank 0 must be the sorted position of "n0"
        spmd.verify_rank_convention(ce.cluster)
        bad = Cluster(local_id="zz")
        bad.add_node(Node(id="aa", uri="x"))
        bad.add_node(Node(id="zz", uri="y"))
        with pytest.raises(spmd.CollectiveError):
            spmd.verify_rank_convention(bad)  # "zz" sorts to rank 1


WORKER = '''
import json, os, random, sys, time, urllib.request
os.environ["JAX_PLATFORMS"] = "cpu"
import re as _re
_fl2 = _re.sub(r"--xla_force_host_platform_device_count=\\d+", "",
               os.environ.get("XLA_FLAGS", ""))
os.environ["XLA_FLAGS"] = (
    _fl2 + " --xla_force_host_platform_device_count=2").strip()
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    pass  # jax < 0.5: the XLA_FLAGS override above covers it

from pilosa_tpu.parallel import multihost, spmd
from pilosa_tpu.server.server import Server
from pilosa_tpu.server.client import InternalClient
from pilosa_tpu.shardwidth import SHARD_WIDTH

multihost.initialize()
pid = jax.process_index()
NPROC = int(os.environ["JAX_NUM_PROCESSES"])
ports = [int(os.environ[f"T_PORT{i}"]) for i in range(NPROC)]
data = os.environ["T_DATA"]

# node ids in sorted order == process ids (the documented convention)
if pid == 0:
    srv = Server(data + "/n0", port=ports[0], name="n0", coordinator=True)
else:
    srv = Server(data + f"/n{pid}", port=ports[pid], name=f"n{pid}",
                 seeds=[f"http://127.0.0.1:{ports[0]}"])
srv.open()
c = InternalClient(timeout=30)

# barrier: both servers joined the HTTP cluster
deadline = time.monotonic() + 60
while len(srv.cluster.sorted_nodes()) < NPROC:
    if time.monotonic() > deadline:
        raise SystemExit("join timeout")
    time.sleep(0.05)
spmd.verify_rank_convention(srv.cluster)

# deterministic dataset, generated identically in both workers for the
# oracle; written once through node 0's HTTP API so fragments land by
# jump hash
N_SHARDS = 6
rng = random.Random(4242)
bits = {}
rows_l, cols_l = [], []
for row in range(3):
    cols = {rng.randrange(N_SHARDS * SHARD_WIDTH) for _ in range(250)}
    bits[row] = cols
    rows_l += [row] * len(cols); cols_l += sorted(cols)
vcols = sorted({rng.randrange(N_SHARDS * SHARD_WIDTH) for _ in range(300)})
vals = {c: rng.randrange(-1000, 100000) for c in vcols}
# time-field data: one month per column, deterministic for the oracle
tcols = sorted({rng.randrange(N_SHARDS * SHARD_WIDTH) for _ in range(200)})
tmonth = {cc: 1 + (i % 9) for i, cc in enumerate(tcols)}
t_oracle = sum(1 for m in tmonth.values() if m >= 3)

if pid == 0:
    post = lambda p, o: c.post_json(srv.uri + p, o)
    post("/index/i", {})
    post("/index/i/field/f", {})
    post("/index/i/field/v",
         {"options": {"type": "int", "min": -1000, "max": 100000}})
    post("/index/i/field/t",
         {"options": {"type": "time", "timeQuantum": "YMD"}})
    post("/index/i/field/f/import", {"rowIDs": rows_l, "columnIDs": cols_l})
    post("/index/i/field/v/import-value",
         {"columnIDs": vcols, "values": [vals[c] for c in vcols]})
    post("/index/i/field/t/import",
         {"rowIDs": [1] * len(tcols), "columnIDs": tcols,
          "timestamps": [f"2019-{tmonth[cc]:02d}-01T00:00"
                         for cc in tcols]})

# barrier: every process waits until the scatter-gather plane sees all
# data, then signals readiness over the CONTROL plane (a file), never a
# jax collective — a global sync enqueued while a peer still drives
# local device work through HTTP deadlocks (the collective parks on
# this process's devices, the peer's HTTP poll needs those devices,
# the peer never reaches the sync: learned the hard way)
want0 = len(bits[0])
deadline = time.monotonic() + 60
while True:
    try:
        got = c.post_json(srv.uri + "/index/i/query",
                          {"query": "Count(Row(f=0))"})["results"][0]
        if got == want0:
            break
    except Exception:
        pass
    if time.monotonic() > deadline:
        raise SystemExit("data visibility timeout")
    time.sleep(0.1)

open(f"{data}/ready.{pid}", "w").write("1")
deadline = time.monotonic() + 120
while not all(os.path.exists(f"{data}/ready.{p}") for p in range(NPROC)):
    if time.monotonic() > deadline:
        raise SystemExit("ready barrier timeout")
    time.sleep(0.05)

# sanity: this process owns only PART of the shard space (stacks must
# genuinely span processes)
plan = spmd.make_plan(
    sorted(srv.holder.index("i").available_shards()),
    spmd.owner_rank_fn(srv.cluster, "i"))
owned = [s for i, s in enumerate(plan.order) if s >= 0 and i in plan.local]
total = [s for s in plan.order if s >= 0]
# every process owns strictly less than the whole space (jump hash may
# legitimately assign SOME process zero shards at small shard counts)
assert len(owned) < len(total), (owned, total)

ce = spmd.CollectiveExecutor(srv.holder, srv.cluster, "i")
out = []
queries = [
    "Count(Row(f=0))",
    "Count(Intersect(Row(f=0), Row(f=1)))",
    "Count(Union(Row(f=0), Row(f=1), Row(f=2)))",
    "Count(Row(v > 50000))",
    "Count(Row(v >< [-500, 0]))",
    "Sum(field=v)",
    "Sum(Row(f=1), field=v)",
    "Min(field=v)",
    "Max(field=v)",
    "TopN(f)",
    "TopN(f, Row(f=0), n=2)",
]
oracle = {
    queries[0]: len(bits[0]),
    queries[1]: len(bits[0] & bits[1]),
    queries[2]: len(bits[0] | bits[1] | bits[2]),
    queries[3]: sum(1 for x in vals.values() if x > 50000),
    queries[4]: sum(1 for x in vals.values() if -500 <= x <= 0),
}
for q in queries:
    got = ce.execute(q)
    if q in oracle:
        assert got == oracle[q], (q, got, oracle[q])
    out.append((q, repr(got)))

# Sum/TopN oracles
sv = ce.execute("Sum(field=v)")
assert sv.val == sum(vals.values()) and sv.count == len(vals)
sf = ce.execute("Sum(Row(f=1), field=v)")
wantf = [v for cc, v in vals.items() if cc in bits[1]]
assert sf.val == sum(wantf) and sf.count == len(wantf)
tn = ce.execute("TopN(f)")
want_tn = sorted(((r, len(cc)) for r, cc in bits.items()),
                 key=lambda rc: (-rc[1], rc[0]))
assert [(p.id, p.count) for p in tn] == want_tn, (tn, want_tn)
mn = ce.execute("Min(field=v)")
lo = min(vals.values())
assert mn.val == lo and mn.count == sum(
    1 for x in vals.values() if x == lo), mn
mx = ce.execute("Max(field=v)")
hi = max(vals.values())
assert mx.val == hi and mx.count == sum(
    1 for x in vals.values() if x == hi), mx
gb = ce.execute("GroupBy(Rows(f))")
want_gb = sorted((r, len(cc)) for r, cc in bits.items() if cc)
assert [(g.group[0].row_id, g.count) for g in gb] == want_gb, gb
# constrained children: limit is a pure cut of the agreed list; column
# resolves via the collective bit gather on the owning shard's process
gbl = ce.execute("GroupBy(Rows(f, limit=2))")
want_gbl = [(r, len(bits[r])) for r in sorted(bits)[:2] if bits[r]]
assert [(g.group[0].row_id, g.count) for g in gbl] == want_gbl, gbl
cc1 = min(bits[1])
gbc = ce.execute(f"GroupBy(Rows(f, column={cc1}))")
want_gbc = [(r, len(bits[r])) for r in sorted(bits) if cc1 in bits[r]]
assert [(g.group[0].row_id, g.count) for g in gbc] == want_gbc, gbc
# TopN post-count args, same lockstep
tnt = ce.execute("TopN(f, Row(f=0), n=2, threshold=1)")
want_tnt = sorted(((r, len(cc & bits[0])) for r, cc in bits.items()),
                  key=lambda rc: (-rc[1], rc[0]))
want_tnt = [(r, cnt) for r, cnt in want_tnt if cnt >= 1][:2]
assert [(p.id, p.count) for p in tnt] == want_tnt, tnt
# bare bitmap results: the global Row gathers replicated; segments
# must match the oracle's columns exactly on EVERY process
br = ce.execute("Row(f=2)")
assert sorted(int(x) for x in br.columns()) == sorted(bits[2]), "bareRow"
br = ce.execute("Union(Row(f=0), Row(f=1))")
assert sorted(int(x) for x in br.columns()) == \
    sorted(bits[0] | bits[1]), "bareUnion"
br = ce.execute("Difference(Row(f=0), Row(f=1), Row(f=2))")
assert sorted(int(x) for x in br.columns()) == \
    sorted(bits[0] - bits[1] - bits[2]), "bareDiff"
# windowed gather (round 5): shrink the per-window bound so the
# 6-shard result replicates in 2-shard sub-plan windows — the window
# sequence must stay in LOCKSTEP across processes (divergence here
# deadlocks the fleet rather than just mismatching)
_saved_gather_bytes = spmd.MAX_ROW_GATHER_BYTES
spmd.MAX_ROW_GATHER_BYTES = 2 * spmd.bm.n_words(SHARD_WIDTH) * 4
try:
    br = ce.execute("Union(Row(f=0), Row(f=1))")
    assert sorted(int(x) for x in br.columns()) == \
        sorted(bits[0] | bits[1]), "windowedUnion"
    br = ce.execute("Row(f=2)")
    assert sorted(int(x) for x in br.columns()) == \
        sorted(bits[2]), "windowedRow"
finally:
    spmd.MAX_ROW_GATHER_BYTES = _saved_gather_bytes
# 4-child GroupBy: outer cartesian lockstep loop across processes
import itertools as _it
gb4 = ce.execute("GroupBy(Rows(f), Rows(f), Rows(f), Rows(f))")
want_gb4 = sorted(
    ((a, b, cc_, d), len(bits[a] & bits[b] & bits[cc_] & bits[d]))
    for a, b, cc_, d in _it.product(sorted(bits), repeat=4)
    if bits[a] & bits[b] & bits[cc_] & bits[d])
assert [tuple(fr.row_id for fr in g.group) for g in gb4] == \
    [k for k, _ in want_gb4], "gb4 keys"
assert [g.count for g in gb4] == [n for _, n in want_gb4], "gb4 counts"

# cross-check the collective data plane against the HTTP control plane.
# Two phases with a control-plane barrier between: an HTTP scatter-
# gather needs the PEER's devices, so it must never run while the peer
# sits in a collective (same deadlock as the ready barrier)
http_res = [c.post_json(srv.uri + "/index/i/query",
                        {"query": q})["results"][0] for q in queries[:5]]
open(f"{data}/xcheck.{pid}", "w").write("1")
deadline = time.monotonic() + 120
while not all(os.path.exists(f"{data}/xcheck.{p}") for p in range(NPROC)):
    if time.monotonic() > deadline:
        raise SystemExit("xcheck barrier timeout")
    time.sleep(0.05)
for q, http in zip(queries[:5], http_res):
    coll = ce.execute(q)
    assert http == coll, (q, http, coll)

# PRODUCT path: a plain HTTP query on the coordinator transparently
# upgrades to a collective — the peer joins via the broadcast bus while
# idling in a pure file-poll loop (no device work, no deadlock)
joined_before = spmd.counters()["collective_joined"]  # pre-barrier snapshot
open(f"{data}/product.{pid}", "w").write("1")
deadline = time.monotonic() + 120
while not all(os.path.exists(f"{data}/product.{p}") for p in range(NPROC)):
    if time.monotonic() > deadline:
        raise SystemExit("product barrier timeout")
    time.sleep(0.05)
if pid == 0:
    # a loaded box can time out one prepare round (legal fallback, the
    # result is exact either way) — require that SOME attempt runs
    # collectively, every attempt stays exact
    before = spmd.counters()["collective_initiated"]
    for attempt in range(5):
        got = c.post_json(srv.uri + "/index/i/query",
                          {"query": queries[1]})["results"][0]
        assert got == oracle[queries[1]], got
        if spmd.counters()["collective_initiated"] > before:
            break
    assert spmd.counters()["collective_initiated"] > before, \
        "no HTTP query ran collectively in 5 attempts"
    # open-ended time range: the coordinator resolves the global view
    # clamp over the control plane (collective-time-bounds round),
    # rewrites the text, and the bounded program runs collectively
    t_pql = "Count(Row(t=1, from='2019-03-01T00:00'))"
    before_t = spmd.counters()["collective_initiated"]
    for attempt in range(5):
        got = c.post_json(srv.uri + "/index/i/query",
                          {"query": t_pql})["results"][0]
        assert got == t_oracle, (got, t_oracle)
        if spmd.counters()["collective_initiated"] > before_t:
            break
    assert spmd.counters()["collective_initiated"] > before_t, \
        "open-ended time query never ran collectively in 5 attempts"
    # bare Row over HTTP: the most ordinary PQL query upgrades to the
    # collective plane end-to-end (translate -> gather -> serialize)
    r_pql = "Union(Row(f=0), Row(f=1))"
    before_r = spmd.counters()["collective_initiated"]
    for attempt in range(5):
        got = c.post_json(srv.uri + "/index/i/query",
                          {"query": r_pql})["results"][0]
        assert sorted(got["columns"]) == sorted(bits[0] | bits[1]), \
            "bare row HTTP result"
        if spmd.counters()["collective_initiated"] > before_r:
            break
    assert spmd.counters()["collective_initiated"] > before_r, \
        "bare row query never ran collectively in 5 attempts"
    assert spmd.counters()["collective_joined"] == 0  # only peers join
    open(f"{data}/product_done.ok", "w").write("1")
else:
    # wait on the coordinator's explicit signal, NOT the joined
    # counter: the xcheck phase's coordinator HTTP queries already ran
    # bus collectives, so the counter is non-zero before this phase —
    # waiting on it let peers race ahead into the refusal drill and
    # poison the coordinator's product attempts (learned from a flake)
    deadline = time.monotonic() + 240
    while not os.path.exists(f"{data}/product_done.ok"):
        if time.monotonic() > deadline:
            raise SystemExit("coordinator product phase timeout")
        time.sleep(0.05)
    # strictly-greater vs the pre-phase snapshot: this phase's
    # collective must have joined THIS peer (poll: the peer's bump can
    # lag the coordinator's return by a bus response)
    deadline = time.monotonic() + 60
    while spmd.counters()["collective_joined"] <= joined_before:
        if time.monotonic() > deadline:
            raise SystemExit("peer never joined the product collective")
        time.sleep(0.05)

# refusal drill: a peer that declines the collective plane (prepare
# returns not-ok) must degrade the coordinator to the scatter-gather
# plane with exact results — the all-or-hang property is handled BEFORE
# anyone enters a device collective
# dynamic phase: interleaved writes and collective reads — every write
# replicates synchronously over the control plane, and the next
# collective must see it (operands build fresh from fragments; no
# cross-query caching to go stale).  Peers serve the bus passively.
open(f"{data}/dynamic.{pid}", "w").write("1")
deadline = time.monotonic() + 120
while not all(os.path.exists(f"{data}/dynamic.{p}") for p in range(NPROC)):
    if time.monotonic() > deadline:
        raise SystemExit("dynamic barrier timeout")
    time.sleep(0.05)
if pid == 0:
    drng = random.Random(7171)
    for it in range(12):
        row = drng.randrange(3)
        col = drng.randrange(N_SHARDS * SHARD_WIDTH)
        if drng.random() < 0.75:
            c.post_json(srv.uri + "/index/i/query",
                        {"query": f"Set({col}, f={row})"})
            bits[row].add(col)
        else:
            c.post_json(srv.uri + "/index/i/query",
                        {"query": f"Clear({col}, f={row})"})
            bits[row].discard(col)
        got = c.post_json(srv.uri + "/index/i/query",
                          {"query": f"Count(Row(f={row}))"})["results"][0]
        assert got == len(bits[row]), (it, got, len(bits[row]))
    open(f"{data}/dynamic_done.ok", "w").write("1")
else:
    deadline = time.monotonic() + 240
    while not os.path.exists(f"{data}/dynamic_done.ok"):
        if time.monotonic() > deadline:
            raise SystemExit("dynamic phase timeout")
        time.sleep(0.05)

orig_avail = spmd.collective_available
if pid == 1:
    spmd.collective_available = lambda: False  # this peer refuses
# patch BEFORE signaling: the coordinator queries the moment the
# barrier opens, and an unpatched peer would let the collective win
open(f"{data}/refuse.{pid}", "w").write("1")
deadline = time.monotonic() + 120
while not all(os.path.exists(f"{data}/refuse.{p}") for p in range(NPROC)):
    if time.monotonic() > deadline:
        raise SystemExit("refuse barrier timeout")
    time.sleep(0.05)
if pid == 0:
    fb0 = spmd.counters()["collective_fallbacks"]
    got = c.post_json(srv.uri + "/index/i/query",
                      {"query": queries[1]})["results"][0]
    assert got == oracle[queries[1]], got
    assert spmd.counters()["collective_fallbacks"] == fb0 + 1, \
        "refusal did not route through the fallback path"
    open(f"{data}/refused.ok", "w").write("1")
else:
    deadline = time.monotonic() + 120
    while not os.path.exists(f"{data}/refused.ok"):
        if time.monotonic() > deadline:
            raise SystemExit("refusal drill timeout")
        time.sleep(0.05)
spmd.collective_available = orig_avail

# exit barrier on the control plane too: a process must not close its
# server while the peer's last collective still needs both sides
open(f"{data}/done.{pid}", "w").write("1")
deadline = time.monotonic() + 120
while not all(os.path.exists(f"{data}/done.{p}") for p in range(NPROC)):
    if time.monotonic() > deadline:
        raise SystemExit("done barrier timeout")
    time.sleep(0.05)
c.close(); srv.close()
print("RESULT " + json.dumps(out))
'''


@pytest.mark.parametrize("n_proc", [2, 3])
def test_multi_process_collective_executor(tmp_path, n_proc):
    """N OS processes, each a full pilosa_tpu server in one HTTP
    cluster; fragments placed by jump hash; Count/Range/Sum/Min/Max/
    TopN/GroupBy run collectively with global stacks spanning every
    process's devices, bit-identical to the Python oracle AND to the
    HTTP scatter-gather plane (the reconciled two-plane story,
    parallel/spmd.py).  The 3-process leg exercises uneven jump-hash
    groups and per-process block padding."""
    import os
    import socket
    import subprocess
    import sys

    socks = [socket.socket() for _ in range(1 + n_proc)]
    try:
        for s in socks:
            s.bind(("127.0.0.1", 0))
        coord_port, *node_ports = (s.getsockname()[1] for s in socks)
    finally:
        for s in socks:
            s.close()

    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    env = dict(os.environ)
    env.update(
        PALLAS_AXON_POOL_IPS="",
        JAX_COORDINATOR_ADDRESS=f"127.0.0.1:{coord_port}",
        JAX_NUM_PROCESSES=str(n_proc),
        T_DATA=str(tmp_path),
        PYTHONPATH=os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))) + os.pathsep
        + env.get("PYTHONPATH", ""),
        **{f"T_PORT{i}": str(p) for i, p in enumerate(node_ports)},
    )
    procs = []
    for pid in range(n_proc):
        e = dict(env, JAX_PROCESS_ID=str(pid))
        procs.append(subprocess.Popen(
            [sys.executable, str(worker)], env=e,
            stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True))
    outs = [p.communicate(timeout=540)[0] for p in procs]
    for p, out in zip(procs, outs):
        if "Multiprocess computations aren't implemented" in out:
            # this jaxlib's CPU backend has no cross-process
            # collectives at all — an environment limitation, not a
            # product regression
            pytest.skip("jax CPU backend lacks multiprocess collectives")
        assert p.returncode == 0, out[-3000:]
    results = {ln for out in outs for ln in out.splitlines()
               if ln.startswith("RESULT ")}
    # every process computed identical (replicated) results
    assert len(results) == 1, results
