"""Engine parity: identical PQL results on 1 device vs the 8-device mesh.

The analog of the reference running every executor op against 1- and
3-node clusters (executor_test.go): the same index, the same queries,
three placement engines —

- ``host``:   stacks stay numpy, counts run the native C++ kernels
- ``single``: stacks on one device, jit kernels, no sharding
- ``mesh``:   stacks sharded over all 8 virtual devices, XLA partitions
              the set algebra + reductions (the multi-chip layout)

Results must be bit-identical across engines and match a Python-set
oracle.  Stack caches are cleared between engines so each run actually
re-places its operands."""

import numpy as np
import pytest

from pilosa_tpu.models.field import Field, FieldOptions
from pilosa_tpu.models.holder import Holder
from pilosa_tpu.parallel.executor import Executor
from pilosa_tpu.shardwidth import SHARD_WIDTH

N_SHARDS = 9  # deliberately not a multiple of 8: exercises mesh padding
N_COLS = N_SHARDS * SHARD_WIDTH


def _place_host(stack):
    return np.ascontiguousarray(stack)


def _place_single(stack):
    import jax

    return jax.device_put(stack, jax.devices()[0])


def _place_mesh(stack):
    from pilosa_tpu.parallel import mesh as pmesh

    return pmesh.shard_stack(pmesh.device_mesh(), stack)


PLACEMENTS = {"host": _place_host, "single": _place_single, "mesh": _place_mesh}


def _clear_stack_caches(holder):
    for idx in holder.indexes.values():
        for f in idx.fields.values():
            with f._lock:
                f._row_stack_cache.clear()
                f._matrix_stack_cache.clear()
            for view in f.views.values():
                for frag in view.fragments.values():
                    with frag._lock:
                        frag._device_cache.clear()
                        frag._stack_cache = None


@pytest.fixture(scope="module")
def data():
    rng = np.random.default_rng(42)
    bits = {}  # (field, row) -> set of cols
    for row in range(6):
        bits[("f", row)] = set(
            int(c) for c in rng.choice(N_COLS, size=800, replace=False))
    # overlap so Intersect/GroupBy are non-trivial
    bits[("f", 1)] |= set(list(bits[("f", 2)])[:200])
    for row in range(3):
        bits[("g", row)] = set(
            int(c) for c in rng.choice(N_COLS, size=500, replace=False))
    vals = {int(c): int(v) for c, v in zip(
        rng.choice(N_COLS, size=1200, replace=False),
        rng.integers(-500, 500, size=1200))}
    return bits, vals


@pytest.fixture(scope="module")
def holder(tmp_path_factory, data):
    bits, vals = data
    h = Holder(str(tmp_path_factory.mktemp("parity") / "h"))
    idx = h.create_index("i")
    f = idx.create_field("f")
    g = idx.create_field("g")
    v = idx.create_field("v", options=FieldOptions.int_field(-500, 500))
    for (fname, row), cols in bits.items():
        fld = f if fname == "f" else g
        cl = sorted(cols)
        fld.import_bits([row] * len(cl), cl)
    v.import_values(sorted(vals), [vals[c] for c in sorted(vals)])
    yield h
    h.close()


QUERIES = [
    "Count(Row(f=1))",
    "Count(Intersect(Row(f=1), Row(f=2)))",
    "Count(Union(Row(f=0), Row(f=3), Row(g=1)))",
    "Count(Difference(Row(f=1), Row(g=0)))",
    "Count(Xor(Row(f=4), Row(g=2)))",
    "Count(Not(Row(f=5)))",
    "Count(Shift(Row(f=1), n=3))",
    "TopN(f, n=4)",
    "TopN(f, Row(g=1), n=3)",
    "Sum(field=v)",
    "Sum(Row(f=1), field=v)",
    "Min(field=v)",
    "Max(field=v)",
    "Count(Row(v > 100))",
    "Count(Row(v <= -250))",
    "Count(Row(v >< [-50, 50]))",
    "MinRow(field=f)",
    "MaxRow(field=f)",
    "Rows(f)",
    "GroupBy(Rows(f))",
    "GroupBy(Rows(f), Rows(g))",
    "GroupBy(Rows(f), Rows(g), filter=Row(f=1))",
    "GroupBy(Rows(g), aggregate=Sum(field=v))",
]


def _run_suite(holder):
    ex = Executor(holder)
    out = []
    for q in QUERIES:
        res = ex.execute("i", q)[0]
        if hasattr(res, "segments"):  # Row result -> column list
            res = res.columns()
        out.append((q, res))
    return out


@pytest.fixture(scope="module")
def engine_results(holder, monkeypatch_module=None):
    results = {}
    orig = Field.__dict__["_place_on_devices"]  # the staticmethod object
    try:
        for name, placer in PLACEMENTS.items():
            Field._place_on_devices = staticmethod(placer)
            _clear_stack_caches(holder)
            results[name] = _run_suite(holder)
    finally:
        setattr(Field, "_place_on_devices", orig)
        _clear_stack_caches(holder)
    return results


@pytest.mark.parametrize("engine", ["host", "mesh"])
def test_engines_match_single_device(engine_results, engine):
    base = engine_results["single"]
    got = engine_results[engine]
    for (q, want), (_, have) in zip(base, got):
        assert have == want, f"{engine} diverges on {q}: {have} != {want}"


def test_oracle_spot_checks(engine_results, data):
    bits, vals = data
    res = dict(engine_results["mesh"])
    assert res["Count(Row(f=1))"] == len(bits[("f", 1)])
    assert res["Count(Intersect(Row(f=1), Row(f=2)))"] == len(
        bits[("f", 1)] & bits[("f", 2)])
    assert res["Count(Union(Row(f=0), Row(f=3), Row(g=1)))"] == len(
        bits[("f", 0)] | bits[("f", 3)] | bits[("g", 1)])
    assert res["Count(Difference(Row(f=1), Row(g=0)))"] == len(
        bits[("f", 1)] - bits[("g", 0)])
    assert res["Sum(field=v)"].val == sum(vals.values())
    assert res["Count(Row(v > 100))"] == sum(1 for x in vals.values() if x > 100)
    # TopN counts descend and match the oracle
    pairs = res["TopN(f, n=4)"]
    counts = {r: len(cs) for (fn, r), cs in bits.items() if fn == "f"}
    assert [p.count for p in pairs] == sorted(
        counts.values(), reverse=True)[:4]


def test_mesh_stacks_actually_sharded(holder):
    import jax

    f = holder.index("i").field("f")
    _clear_stack_caches(holder)
    stack = f.device_row_stack(1, tuple(range(N_SHARDS)))
    assert len(stack.sharding.device_set) == len(jax.devices())
