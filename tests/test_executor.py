"""Executor tests: the full PQL op table against in-memory holders.

Mirrors the reference's executor_test.go black-box coverage (4,138 LoC of
per-op tests against 1- and 3-node clusters; round 1 covers the
single-node paths here, cluster paths under tests/test_cluster*).
"""

import random

import numpy as np
import pytest

from pilosa_tpu.models import FieldOptions, Holder, IndexOptions
from pilosa_tpu.parallel import Executor, ExecOptions
from pilosa_tpu.parallel.results import GroupCount, Pair, ValCount
from pilosa_tpu.shardwidth import SHARD_WIDTH


@pytest.fixture()
def holder():
    h = Holder(None)
    h.create_index("i", IndexOptions())
    return h


@pytest.fixture()
def ex(holder):
    return Executor(holder)


def q(ex, src, **kw):
    return ex.execute("i", src, **kw)[0]


def columns(row):
    return list(int(c) for c in row.columns())


# ------------------------------------------------------------------ writes


def test_set_and_row(ex, holder):
    holder.index("i").create_field("f")
    assert q(ex, "Set(3, f=10)") is True
    assert q(ex, "Set(3, f=10)") is False  # already set
    assert columns(q(ex, "Row(f=10)")) == [3]


def test_set_auto_field_missing(ex):
    with pytest.raises(Exception):
        q(ex, "Row(missing=1)")


def test_set_multi_shard(ex, holder):
    idx = holder.index("i")
    idx.create_field("f")
    for col in (3, SHARD_WIDTH + 1, 2 * SHARD_WIDTH + 5):
        q(ex, f"Set({col}, f=10)")
    assert columns(q(ex, "Row(f=10)")) == [3, SHARD_WIDTH + 1, 2 * SHARD_WIDTH + 5]


def test_clear(ex, holder):
    holder.index("i").create_field("f")
    q(ex, "Set(3, f=10)")
    assert q(ex, "Clear(3, f=10)") is True
    assert q(ex, "Clear(3, f=10)") is False
    assert columns(q(ex, "Row(f=10)")) == []


def test_clear_row(ex, holder):
    holder.index("i").create_field("f")
    for col in (1, 2, SHARD_WIDTH + 3):
        q(ex, f"Set({col}, f=10)")
    q(ex, "Set(1, f=11)")
    assert q(ex, "ClearRow(f=10)") is True
    assert columns(q(ex, "Row(f=10)")) == []
    assert columns(q(ex, "Row(f=11)")) == [1]


def test_store(ex, holder):
    holder.index("i").create_field("f")
    q(ex, "Set(1, f=10)")
    q(ex, f"Set({SHARD_WIDTH + 2}, f=10)")
    assert q(ex, "Store(Row(f=10), f=20)") is True
    assert columns(q(ex, "Row(f=20)")) == [1, SHARD_WIDTH + 2]
    # Store overwrites
    q(ex, "Set(5, f=11)")
    q(ex, "Store(Row(f=11), f=20)")
    assert columns(q(ex, "Row(f=20)")) == [5]


def test_set_value_and_conditions(ex, holder):
    holder.index("i").create_field("amount", FieldOptions.int_field(-1000, 1000))
    q(ex, "Set(1, amount=300)")
    q(ex, "Set(2, amount=-150)")
    q(ex, "Set(3, amount=300)")
    assert columns(q(ex, "Row(amount == 300)")) == [1, 3]
    assert columns(q(ex, "Row(amount != 300)")) == [2]
    assert columns(q(ex, "Row(amount < 0)")) == [2]
    assert columns(q(ex, "Row(amount >= -150)")) == [1, 2, 3]
    assert columns(q(ex, "Row(-200 < amount < 400)")) == [1, 2, 3]
    assert columns(q(ex, "Row(amount >< [0, 299])")) == []
    assert columns(q(ex, "Row(amount != null)")) == [1, 2, 3]


# ----------------------------------------------------------------- bitmaps


@pytest.fixture()
def populated(ex, holder):
    idx = holder.index("i")
    idx.create_field("a")
    idx.create_field("b")
    # row a=1: cols {1,2,3, W+1}; row b=1: cols {2,3, W+2}
    for col in (1, 2, 3, SHARD_WIDTH + 1):
        q(ex, f"Set({col}, a=1)")
    for col in (2, 3, SHARD_WIDTH + 2):
        q(ex, f"Set({col}, b=1)")
    return ex


def test_union_intersect_difference_xor(populated):
    ex = populated
    W = SHARD_WIDTH
    assert columns(q(ex, "Union(Row(a=1), Row(b=1))")) == [1, 2, 3, W + 1, W + 2]
    assert columns(q(ex, "Intersect(Row(a=1), Row(b=1))")) == [2, 3]
    assert columns(q(ex, "Difference(Row(a=1), Row(b=1))")) == [1, W + 1]
    assert columns(q(ex, "Xor(Row(a=1), Row(b=1))")) == [1, W + 1, W + 2]
    assert columns(q(ex, "Union()")) == []
    with pytest.raises(Exception):
        q(ex, "Intersect()")


def test_count(populated):
    assert q(populated, "Count(Row(a=1))") == 4
    assert q(populated, "Count(Intersect(Row(a=1), Row(b=1)))") == 2
    assert q(populated, "Count(Union(Row(a=1), Row(b=1)))") == 5


def test_not(populated):
    ex = populated
    # existence tracks all set columns
    got = columns(q(ex, "Not(Row(a=1))"))
    assert got == [SHARD_WIDTH + 2]
    got = columns(q(ex, "Not(Union(Row(a=1), Row(b=1)))"))
    assert got == []


def test_shift(populated):
    assert columns(q(populated, "Shift(Row(a=1), n=2)")) == [
        3, 4, 5, SHARD_WIDTH + 3,
    ]
    assert columns(q(populated, "Shift(Row(a=1))")) == [2, 3, 4, SHARD_WIDTH + 2]


def test_row_on_missing_shard_option(populated):
    got = q(populated, "Options(Row(a=1), shards=[1])")
    assert columns(got) == [SHARD_WIDTH + 1]


def test_options_unknown_arg(populated):
    with pytest.raises(Exception):
        q(populated, "Options(Row(a=1), wat=true)")


# -------------------------------------------------------------- time range


def test_row_time_range(ex, holder):
    holder.index("i").create_field("t", FieldOptions.time_field("YMDH"))
    q(ex, "Set(1, t=10, 2018-01-01T00:00)")
    q(ex, "Set(2, t=10, 2018-02-01T00:00)")
    q(ex, "Set(3, t=10, 2019-01-01T00:00)")
    got = q(ex, "Row(t=10, from='2018-01-01T00:00', to='2018-12-31T00:00')")
    assert columns(got) == [1, 2]
    got = q(ex, "Row(t=10, from='2019-01-01T00:00', to='2020-01-01T00:00')")
    assert columns(got) == [3]
    # open-ended ranges clamp to existing views
    got = q(ex, "Row(t=10, to='2018-06-01T00:00')")
    assert columns(got) == [1, 2]
    got = q(ex, "Row(t=10, from='2018-06-01T00:00')")
    assert columns(got) == [3]
    # plain row query sees the standard view
    assert columns(q(ex, "Row(t=10)")) == [1, 2, 3]
    # legacy Range form
    got = q(ex, "Range(t=10, 2018-01-01T00:00, 2018-12-31T00:00)")
    assert columns(got) == [1, 2]


# ------------------------------------------------------------- aggregates


def test_sum_min_max(ex, holder):
    holder.index("i").create_field("n", FieldOptions.int_field(-100, 100))
    holder.index("i").create_field("f")
    data = {1: 10, 2: -5, 3: 42, SHARD_WIDTH + 1: 42}
    for col, v in data.items():
        q(ex, f"Set({col}, n={v})")
    q(ex, "Set(1, f=7)")
    q(ex, "Set(2, f=7)")

    assert q(ex, "Sum(field=n)") == ValCount(sum(data.values()), 4)
    assert q(ex, "Min(field=n)") == ValCount(-5, 1)
    assert q(ex, "Max(field=n)") == ValCount(42, 2)
    # filtered
    assert q(ex, "Sum(Row(f=7), field=n)") == ValCount(5, 2)
    assert q(ex, "Min(Row(f=7), field=n)") == ValCount(-5, 1)
    assert q(ex, "Max(Row(f=7), field=n)") == ValCount(10, 1)


def test_min_row_max_row(populated):
    ex = populated
    q(ex, "Set(9, a=5)")
    assert q(ex, "MinRow(field=a)") == Pair(id=1, count=4)
    assert q(ex, "MaxRow(field=a)") == Pair(id=5, count=1)
    got = q(ex, "MinRow(Row(b=1), field=a)")
    assert got == Pair(id=1, count=2)


# ------------------------------------------------------------ TopN / Rows


def test_topn(ex, holder):
    holder.index("i").create_field("f")
    counts = {10: 5, 11: 3, 12: 8, 13: 1}
    col = 0
    for row, n in counts.items():
        for _ in range(n):
            q(ex, f"Set({col}, f={row})")
            col += 1
    got = q(ex, "TopN(f, n=2)")
    assert got == [Pair(id=12, count=8), Pair(id=10, count=5)]
    got = q(ex, "TopN(f)")
    assert [p.id for p in got] == [12, 10, 11, 13]
    # across shards
    q(ex, f"Set({SHARD_WIDTH + 1}, f=11)")
    q(ex, f"Set({SHARD_WIDTH + 2}, f=11)")
    got = q(ex, "TopN(f, n=2)")
    assert got == [Pair(id=12, count=8), Pair(id=10, count=5)]
    got = q(ex, "TopN(f, n=3)")
    assert got[2] == Pair(id=11, count=5)
    # with filter
    got = q(ex, "TopN(f, Row(f=12), n=1)")
    assert got == [Pair(id=12, count=8)]
    # ids restriction & threshold
    got = q(ex, "TopN(f, ids=[10, 13])")
    assert got == [Pair(id=10, count=5), Pair(id=13, count=1)]
    got = q(ex, "TopN(f, threshold=5)")
    assert [p.id for p in got] == [12, 10, 11]


def test_topn_attr_filter(ex, holder):
    holder.index("i").create_field("f")
    q(ex, "Set(1, f=10)")
    q(ex, "Set(2, f=11)")
    q(ex, 'SetRowAttrs(f, 10, category="x")')
    q(ex, 'SetRowAttrs(f, 11, category="y")')
    got = q(ex, 'TopN(f, attrName="category", attrValues=["x"])')
    assert got == [Pair(id=10, count=1)]


def test_rows(ex, holder):
    holder.index("i").create_field("f")
    for row in (1, 5, 9):
        q(ex, f"Set(0, f={row})")
    q(ex, f"Set({SHARD_WIDTH + 1}, f=12)")
    assert q(ex, "Rows(f)") == [1, 5, 9, 12]
    assert q(ex, "Rows(f, previous=5)") == [9, 12]
    assert q(ex, "Rows(f, limit=2)") == [1, 5]
    assert q(ex, "Rows(f, column=0)") == [1, 5, 9]
    assert q(ex, f"Rows(f, column={SHARD_WIDTH + 1})") == [12]


def test_rows_time_range(ex, holder):
    """Rows(from=, to=) on a time field scans the covering time views
    with open ends clamped to the existing views' min/max; non-time
    fields ignore from/to (reference executeRowsShard,
    executor.go:1319-1400)."""
    from pilosa_tpu.models.field import FieldOptions

    holder.index("i").create_field("t", FieldOptions.time_field("YMDH"))
    q(ex, "Set(1, t=0, 2019-01-05T08:00)")
    q(ex, "Set(2, t=1, 2019-03-05T08:00)")
    q(ex, "Set(3, t=2, 2019-06-05T08:00)")
    assert q(ex, "Rows(t)") == [0, 1, 2]
    assert q(ex, "Rows(t, from='2019-01-01T00:00', "
                 "to='2019-04-01T00:00')") == [0, 1]
    # open ends clamp to the min/max existing views
    assert q(ex, "Rows(t, to='2019-02-01T00:00')") == [0]
    assert q(ex, "Rows(t, from='2019-02-01T00:00')") == [1, 2]
    # previous/limit/column compose with the time cover
    assert q(ex, "Rows(t, from='2019-01-01T00:00', "
                 "to='2019-04-01T00:00', limit=1)") == [0]
    assert q(ex, "Rows(t, from='2019-01-01T00:00', "
                 "to='2019-04-01T00:00', column=2)") == [1]
    # non-time field: from/to ignored, exactly as the reference
    holder.index("i").create_field("nt")
    q(ex, "Set(5, nt=7)")
    assert q(ex, "Rows(nt, from='2019-01-01T00:00')") == [7]
    # GroupBy child restriction (limit/column present) sees the cover
    got = q(ex, "GroupBy(Rows(t, from='2019-01-01T00:00', "
                "to='2019-04-01T00:00', limit=5))")
    assert [gc.group[0].row_id for gc in got] == [0, 1]
    # no_standard_view: Rows scans the time cover, but GroupBy's
    # counting stage requires the standard fragment and yields [] —
    # the REFERENCE behaves identically (newGroupByIterator fetches
    # viewStandard and bails when nil, executor.go:3107-3109), so the
    # apparent contradiction is pinned parity, not a bug
    holder.index("i").create_field(
        "tnsv", FieldOptions.time_field("YMDH", no_standard_view=True))
    q(ex, "Set(1, tnsv=0, 2019-01-05T08:00)")
    q(ex, "Set(2, tnsv=1, 2019-03-05T08:00)")
    assert q(ex, "Rows(tnsv)") == [0, 1]
    assert q(ex, "GroupBy(Rows(tnsv))") == []


def test_rows_limit_pushdown_bounds_per_shard_transfer(ex, holder):
    """Rows(limit=) at high row cardinality: limit/previous apply inside
    each shard scan and the merge stops at the limit (reference
    executor.go:1040-1071) — no shard ships its full row set and no host
    union of all rows is built (VERDICT round-2 weak #4)."""
    f = holder.index("i").create_field("f")
    n_shards, rows_per_shard = 4, 500
    want = set()
    rows_l, cols_l = [], []
    for s in range(n_shards):
        for r in range(rows_per_shard):
            # disjoint odd/even row ids per shard parity so the merge
            # genuinely interleaves across shards
            rid = r * 2 + (s % 2)
            rows_l.append(rid)
            cols_l.append(s * SHARD_WIDTH + r)
            want.add(rid)
    f.import_bits(rows_l, cols_l)

    all_rows = sorted(want)
    captured: list[list[int]] = []
    orig = ex._map_shards

    def spy(fn, shards, **kw):
        parts = orig(fn, shards, **kw)
        captured.append([len(p) for p in parts])
        return parts

    ex._map_shards = spy
    try:
        assert q(ex, "Rows(f, limit=7)") == all_rows[:7]
        # every shard truncated its scan to the limit
        assert captured and all(n <= 7 for n in captured[-1])
        prev = all_rows[100]
        got = q(ex, f"Rows(f, previous={prev}, limit=9)")
        assert got == [r for r in all_rows if r > prev][:9]
        assert all(n <= 9 for n in captured[-1])
        # unlimited stays exact
        assert q(ex, "Rows(f)") == all_rows
    finally:
        ex._map_shards = orig


# ---------------------------------------------------------------- GroupBy


def test_group_by(ex, holder):
    idx = holder.index("i")
    idx.create_field("a")
    idx.create_field("b")
    # a rows: 0 {1,2,3}; 1 {2,3}; b rows: 0 {1,2}, 1 {3}
    for col in (1, 2, 3):
        q(ex, f"Set({col}, a=0)")
    for col in (2, 3):
        q(ex, f"Set({col}, a=1)")
    for col in (1, 2):
        q(ex, f"Set({col}, b=0)")
    q(ex, "Set(3, b=1)")

    got = q(ex, "GroupBy(Rows(a), Rows(b))")
    want = [
        GroupCount([_fr("a", 0), _fr("b", 0)], 2),
        GroupCount([_fr("a", 0), _fr("b", 1)], 1),
        GroupCount([_fr("a", 1), _fr("b", 0)], 1),
        GroupCount([_fr("a", 1), _fr("b", 1)], 1),
    ]
    assert got == want

    got = q(ex, "GroupBy(Rows(a), Rows(b), filter=Row(b=0))")
    assert got == [
        GroupCount([_fr("a", 0), _fr("b", 0)], 2),
        GroupCount([_fr("a", 1), _fr("b", 0)], 1),
    ]

    got = q(ex, "GroupBy(Rows(a), Rows(b), limit=1)")
    assert got == [GroupCount([_fr("a", 0), _fr("b", 0)], 2)]


def _fr(field, row):
    from pilosa_tpu.parallel.results import FieldRow

    return FieldRow(field=field, row_id=row)


# ------------------------------------------------------------------ attrs


def test_row_attrs_attach(ex, holder):
    holder.index("i").create_field("f")
    q(ex, "Set(1, f=10)")
    q(ex, 'SetRowAttrs(f, 10, color="blue", weight=3)')
    row = q(ex, "Row(f=10)")
    assert row.attrs == {"color": "blue", "weight": 3}
    # excluded when requested
    row = q(ex, "Options(Row(f=10), excludeRowAttrs=true)")
    assert row.attrs == {}


def test_column_attrs_store(ex, holder):
    q_ = ex.execute("i", 'SetColumnAttrs(9, name="col9")')
    assert holder.index("i").column_attrs.attrs(9) == {"name": "col9"}


# ------------------------------------------------------------------ misc


def test_bool_field_pql_literals(ex, holder):
    holder.index("i").create_field("b", FieldOptions.bool_field())
    assert q(ex, "Set(1, b=true)") is True
    assert q(ex, "Set(2, b=false)") is True
    assert columns(q(ex, "Row(b=true)")) == [1]
    assert columns(q(ex, "Row(b=false)")) == [2]
    assert q(ex, "Clear(1, b=true)") is True
    assert columns(q(ex, "Row(b=true)")) == []


def test_failed_set_leaves_no_phantom_existence(ex, holder):
    holder.index("i").create_field("f")
    with pytest.raises(Exception):
        q(ex, 'Set(7, f="not-an-int")')
    with pytest.raises(Exception):
        q(ex, "Set(8, f=1, 2018-01-01T00:00)")  # timestamp on non-time field
    ef = holder.index("i").existence_field()
    assert ef.row(0, 0) is None or not ef.row(0, 0).any()


def test_store_skips_empty_shards(ex, holder):
    holder.index("i").create_field("a")
    holder.index("i").create_field("t")
    q(ex, "Set(1, a=1)")
    q(ex, f"Set({SHARD_WIDTH * 3 + 1}, a=2)")  # other field shards: 0 and 3
    assert q(ex, "Store(Row(a=1), t=9)") is True
    view = holder.index("i").field("t").views["standard"]
    assert sorted(view.fragments) == [0]  # no empty fragments on shard 3
    # storing the identical row again is a no-op
    assert q(ex, "Store(Row(a=1), t=9)") is False


def test_attr_store_cross_thread(holder):
    from concurrent.futures import ThreadPoolExecutor as TPE

    store = holder.index("i").column_attrs
    store.set_attrs(1, {"x": 1})
    with TPE(max_workers=1) as pool:
        got = pool.submit(store.attrs, 1).result()
    assert got == {"x": 1}


def test_multiple_calls_one_query(ex, holder):
    holder.index("i").create_field("f")
    results = ex.execute("i", "Set(1, f=2) Set(2, f=2) Count(Row(f=2))")
    assert results == [True, True, 2]


def test_unknown_call(ex):
    with pytest.raises(Exception):
        q(ex, "Frobnicate(Row(f=1))")


class TestGroupByChildConstraints:
    """GroupBy children with limit/column pre-execute cluster-wide and
    restrict the walk (reference executeGroupBy, executor.go:1084-1117),
    and 'field=' spells the Rows field (back-compat)."""

    @pytest.fixture
    def gex(self, tmp_path):
        holder = Holder(str(tmp_path / "g"))
        idx = holder.create_index("g")
        self.sets = {"a": {}, "b": {}}
        rng = random.Random(2)
        for fname in self.sets:
            f = idx.create_field(fname)
            rows, cols = [], []
            for row in range(6):
                members = {rng.randrange(3 * SHARD_WIDTH)
                           for _ in range(120)}
                self.sets[fname][row] = members
                rows.extend([row] * len(members))
                cols.extend(members)
            f.import_bits(rows, cols)
        yield Executor(holder)
        holder.close()

    def _want(self, a_rows, b_rows):
        out = {}
        for ra in a_rows:
            for rb in b_rows:
                c = len(self.sets["a"][ra] & self.sets["b"][rb])
                if c:
                    out[(ra, rb)] = c
        return out

    def test_child_limit(self, gex):
        got = gex.execute("g", "GroupBy(Rows(a, limit=2), Rows(b))")[0]
        want = self._want([0, 1], range(6))
        assert {(g.group[0].row_id, g.group[1].row_id): g.count
                for g in got} == want

    def test_child_column(self, gex):
        col = next(iter(self.sets["a"][3]))
        a_rows = [r for r, s in self.sets["a"].items() if col in s]
        got = gex.execute("g", f"GroupBy(Rows(a, column={col}), Rows(b))")[0]
        want = self._want(a_rows, range(6))
        assert {(g.group[0].row_id, g.group[1].row_id): g.count
                for g in got} == want

    def test_child_column_unset_means_no_groups(self, gex):
        # a column provably outside every generated set (fixture draws
        # from [0, 3*SHARD_WIDTH)): no rows contain it -> no groups
        col = 3 * SHARD_WIDTH + 1
        assert all(col not in s for s in self.sets["a"].values())
        got = gex.execute("g", f"GroupBy(Rows(a, column={col}), Rows(b))")
        assert got[0] == []

    def test_child_previous(self, gex):
        got = gex.execute("g", "GroupBy(Rows(a, previous=2), Rows(b))")[0]
        want = self._want([3, 4, 5], range(6))
        assert {(g.group[0].row_id, g.group[1].row_id): g.count
                for g in got} == want

    def test_field_arg_spelling(self, gex):
        a = gex.execute("g", "GroupBy(Rows(a), Rows(b))")[0]
        b = gex.execute("g", "GroupBy(Rows(field=a), Rows(field=b))")[0]
        assert [(g.group[0].row_id, g.group[1].row_id, g.count)
                for g in a] == \
            [(g.group[0].row_id, g.group[1].row_id, g.count) for g in b]

    def test_groupby_offset(self, tmp_path):
        holder = Holder(str(tmp_path / "o"))
        idx = holder.create_index("o")
        # dense overlap: every (a-row, b-row) pair intersects
        for fname in ("a", "b"):
            f = idx.create_field(fname)
            rows, cols = [], []
            for row in range(4):
                for c in range(0, 200, 2):
                    rows.append(row)
                    cols.append(c)
            f.import_bits(rows, cols)
        ex = Executor(holder)
        full = ex.execute("o", "GroupBy(Rows(a), Rows(b))")[0]
        assert len(full) == 16
        key = lambda g: tuple((fr.field, fr.row_id) for fr in g.group)
        off = ex.execute("o", "GroupBy(Rows(a), Rows(b), offset=3)")[0]
        assert [key(g) for g in off] == [key(g) for g in full][3:]
        both = ex.execute(
            "o", "GroupBy(Rows(a), Rows(b), offset=2, limit=4)")[0]
        assert [key(g) for g in both] == [key(g) for g in full][2:6]
        # reference quirk: offset >= len leaves results unchanged
        # (executor.go:1138 only slices when offset < len)
        huge = ex.execute(
            "o", f"GroupBy(Rows(a), Rows(b), offset={len(full) + 5})")[0]
        assert [key(g) for g in huge] == [key(g) for g in full]
        holder.close()


class TestTopNTanimoto:
    def test_tanimoto_window(self, tmp_path):
        """tanimotoThreshold keeps rows whose full count lies strictly
        inside (|src|*T/100, |src|*100/T), ranked by intersection count
        (reference fragment.top, fragment.go:1588-1617, applied to
        global counts here)."""
        holder = Holder(str(tmp_path / "t"))
        idx = holder.create_index("t")
        f = idx.create_field("f")
        src_field = idx.create_field("s")
        # src: 10 columns
        src_cols = list(range(0, 1000, 100))
        src_field.import_bits([1] * 10, src_cols)
        # rows with controlled full counts and overlaps
        layouts = {
            0: list(range(0, 2000, 100)),   # count 20 = hi -> window excludes (strict)
            1: list(range(0, 900, 100)),    # count 9, inter 9: coeff ceil(900/10)=90 > 50
            2: list(range(0, 400, 100)),    # count 4 < lo=5 -> window excludes
            3: ([c + 1 for c in range(0, 1000, 100)]
                + list(range(0, 500, 100))),  # count 15, inter 5:
            # coeff ceil(500/(15+10-5)) = 25 <= 50 -> coefficient excludes
        }
        for r, cols in layouts.items():
            f.import_bits([r] * len(cols), cols)
        ex = Executor(holder)
        got = ex.execute("t", "TopN(f, Row(s=1), tanimotoThreshold=50)")[0]
        # |src| = 10 -> window (5, 20); then the exact coefficient
        # check: only row 1 survives both
        assert [(p.count, p.id) for p in got] == [(9, 1)]
        with pytest.raises(Exception):
            ex.execute("t", "TopN(f, Row(s=1), tanimotoThreshold=101)")
        holder.close()
