#!/usr/bin/env python
"""Collective-plane vs scatter-plane latency measurement.

The SPMD collective plane (parallel/spmd.py) has long-haul CORRECTNESS
evidence (tools/soak_spmd.py); this records its PERFORMANCE envelope
against the scatter plane on the same cluster and dataset — per-query
p50/p95 latency over real OS processes, every answer cross-checked
between planes before anything is timed.

What each plane pays per query:
  - scatter: the origin fans sub-queries to every owner over HTTP and
    reduces (reference executor.go:2455's shape) — N-1 HTTP round
    trips, results ride the wire;
  - collective: every process enters one jitted program over the
    global mesh in lockstep; coordination is a tiny prepare broadcast
    on the control plane, data never leaves device order.

On this one-core CI box all processes share one core, so collective
numbers carry the serialization of P processes' compute — the record
is an honest protocol-overhead envelope, not an ICI scaling claim
(that needs real multi-host hardware; BASELINE.md says so).

Usage: python benchmarks/measure_spmd.py [--procs 2] [--reps 40]
Prints one JSON line per (query, plane-pair) plus a summary line.

The fleet scaffolding (file barrier, port allocation, spawn with
kill-the-whole-fleet-on-timeout) is shared with tools/soak_spmd.py via
tools/fleet_lib.py — change the discipline THERE, once.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = r'''
import json, os, random, statistics, sys, time
os.environ["JAX_PLATFORMS"] = "cpu"
import re as _re
_fl2 = _re.sub(r"--xla_force_host_platform_device_count=\d+", "",
               os.environ.get("XLA_FLAGS", ""))
os.environ["XLA_FLAGS"] = (
    _fl2 + " --xla_force_host_platform_device_count=2").strip()
import jax
jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 2)
except AttributeError:
    pass  # jax < 0.5: the XLA_FLAGS override above covers it

from pilosa_tpu.parallel import multihost, spmd
from pilosa_tpu.pql import parse
from pilosa_tpu.server.server import Server
from pilosa_tpu.server.client import InternalClient
from pilosa_tpu.shardwidth import SHARD_WIDTH

multihost.initialize()
pid = jax.process_index()
NPROC = int(os.environ["JAX_NUM_PROCESSES"])
ports = [int(os.environ[f"T_PORT{i}"]) for i in range(NPROC)]
data = os.environ["T_DATA"]
REPS = int(os.environ["M_REPS"])
SEED = int(os.environ["M_SEED"])
N_SHARDS = 8
VMIN, VMAX = -10000, 100000

if pid == 0:
    srv = Server(data + "/n0", port=ports[0], name="n0", coordinator=True)
else:
    srv = Server(data + f"/n{pid}", port=ports[pid], name=f"n{pid}",
                 seeds=[f"http://127.0.0.1:{ports[0]}"])
srv.open()
c = InternalClient(timeout=120)

deadline = time.monotonic() + 60
while len(srv.cluster.sorted_nodes()) < NPROC:
    if time.monotonic() > deadline:
        raise SystemExit("join timeout")
    time.sleep(0.05)
spmd.verify_rank_convention(srv.cluster)


from tools import fleet_lib as _fl
from tools.fleet_lib import file_barrier


def barrier(name, timeout=600):
    file_barrier(data, name, pid, NPROC, timeout)


# ---- deterministic dataset, identical in every process ----
rng = random.Random(SEED)
bits = {}
for fi in range(3):
    for row in range(8):
        bits[(f"f{fi}", row)] = {
            rng.randrange(N_SHARDS * SHARD_WIDTH) for _ in range(2000)}
vcols = sorted({rng.randrange(N_SHARDS * SHARD_WIDTH)
                for _ in range(5000)})
vals = {cc: rng.randrange(VMIN, VMAX) for cc in vcols}

if pid == 0:
    post = lambda p, o: c.post_json(srv.uri + p, o)
    post("/index/i", {})
    for fi in range(3):
        post(f"/index/i/field/f{fi}", {})
        rows_l, cols_l = [], []
        for row in range(8):
            cs = sorted(bits[(f"f{fi}", row)])
            rows_l += [row] * len(cs)
            cols_l += cs
        post(f"/index/i/field/f{fi}/import",
             {"rowIDs": rows_l, "columnIDs": cols_l})
    post("/index/i/field/v",
         {"options": {"type": "int", "min": VMIN, "max": VMAX}})
    post("/index/i/field/v/import-value",
         {"columnIDs": vcols, "values": [vals[cc] for cc in vcols]})

want0 = len(bits[("f0", 0)])
end = time.monotonic() + 180
while True:
    try:
        got = c.post_json(srv.uri + "/index/i/query",
                          {"query": "Count(Row(f0=0))"})["results"][0]
        if got == want0:
            break
    except Exception:
        pass
    if time.monotonic() > end:
        raise SystemExit("data visibility timeout")
    time.sleep(0.1)
barrier("loaded")

ce = spmd.CollectiveExecutor(srv.holder, srv.cluster, "i")

QUERIES = [
    ("count_tree",
     "Count(Intersect(Row(f0=0), Union(Row(f1=1), Row(f2=2))))"),
    ("bsi_condition", "Count(Row(v > 40000))"),
    ("sum_filtered", "Sum(Row(f0=1), field=v)"),
    ("topn", "TopN(f0)"),
    ("groupby_2child", "GroupBy(Rows(f0), Rows(f1))"),
    # round-4 additions: the ordinary-read surface
    ("bare_row", "Row(f0=0)"),
    ("bare_union", "Union(Row(f0=0), Row(f1=1))"),
    ("groupby_4child", "GroupBy(Rows(f0), Rows(f1), Rows(f2), Rows(f0))"),
    ("rows", "Rows(f0)"),
    ("minrow", "MinRow(field=f0)"),
]


# plane-comparable normalization is SHARED with the SPMD soak
# (tools/fleet_lib.norm_result / norm_http_result) so the two
# harnesses' cross-check conventions can never drift
norm = _fl.norm_result


def norm_http(name, raw):
    return _fl.norm_http_result(raw)


out = []
for name, q in QUERIES:
    call = parse(q).calls[0]
    assert ce.supported(call), f"{name} not collective-supported"

    # warm both planes (compile + stack build), then CROSS-CHECK the
    # answers before timing anything
    coll = ce.execute(q)
    barrier(f"warm.{name}")
    if pid == 0:
        raw = c.post_json(srv.uri + "/index/i/query",
                          {"query": q})["results"][0]
        assert norm(coll) == norm_http(name, raw), (
            name, norm(coll), norm_http(name, raw))
    # peers MUST idle at a control-plane barrier while the coordinator
    # scatter-queries: a peer that advanced into the collective timing
    # loop parks its devices, the scatter sub-query to that peer can't
    # be served, and the fleet deadlocks (the spmd plane's documented
    # rule: barriers gating collective entry ride the control plane)
    barrier(f"xchk.{name}")

    # collective plane: every process runs the identical rep sequence
    # in lockstep; the coordinator records per-rep wall time
    lat_c = []
    for _ in range(REPS):
        t0 = time.perf_counter()
        ce.execute(q)
        lat_c.append(time.perf_counter() - t0)
    barrier(f"coll.{name}")

    # scatter plane: coordinator posts over HTTP, peers idle/serving
    lat_s = []
    if pid == 0:
        for _ in range(REPS):
            t0 = time.perf_counter()
            c.post_json(srv.uri + "/index/i/query", {"query": q})
            lat_s.append(time.perf_counter() - t0)
    barrier(f"scat.{name}")

    if pid == 0:
        qs = lambda xs, p: statistics.quantiles(xs, n=100)[p - 1] * 1e3
        out.append({
            "query": name,
            "collective_p50_ms": round(qs(lat_c, 50), 2),
            "collective_p95_ms": round(qs(lat_c, 95), 2),
            "scatter_p50_ms": round(qs(lat_s, 50), 2),
            "scatter_p95_ms": round(qs(lat_s, 95), 2),
            "reps": REPS,
        })

barrier("done")
c.close(); srv.close()
if pid == 0:
    print("RESULT " + json.dumps(out))
'''


sys.path.insert(0, REPO)
from tools.fleet_lib import free_ports, run_fleet  # noqa: E402


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--reps", type=int, default=40)
    ap.add_argument("--seed", type=int, default=12348)
    args = ap.parse_args()

    n = args.procs
    with tempfile.TemporaryDirectory() as data:
        coord_port, *http_ports = free_ports(1 + n)
        env = {
            **os.environ,
            "JAX_PLATFORMS": "cpu",
            "PALLAS_AXON_POOL_IPS": "",  # never init the axon plugin
            "PYTHONPATH": REPO + os.pathsep + os.environ.get("PYTHONPATH", ""),
            "JAX_NUM_PROCESSES": str(n),
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{coord_port}",
            "T_DATA": data,
            "M_REPS": str(args.reps),
            "M_SEED": str(args.seed),
            # On a one-core box, P concurrent XLA compiles can starve a
            # worker's coordination heartbeat past the 100 s default and
            # the runtime fail-stops the fleet (observed at procs=3) —
            # the measurement needs the fleet to survive its own compile
            # storm, so widen the window unless the caller pinned one.
            "PILOSA_TPU_DIST_HEARTBEAT_S": os.environ.get(
                "PILOSA_TPU_DIST_HEARTBEAT_S", "600"),
            "PILOSA_TPU_SHARD_WIDTH_EXP": os.environ.get(
                "PILOSA_TPU_SHARD_WIDTH_EXP", "16"),
        }
        for i, p in enumerate(http_ports):
            env[f"T_PORT{i}"] = str(p)
        ok, outs, _timed_out = run_fleet(
            [[sys.executable, "-u", "-c", WORKER] for _ in range(n)],
            [{**env, "JAX_PROCESS_ID": str(i)} for i in range(n)],
            timeout=900, label="measure_spmd", cwd=REPO)
        if not ok:
            return 1
        for line in outs[0].splitlines():
            if line.startswith("RESULT "):
                rows = json.loads(line[len("RESULT "):])
                for row in rows:
                    print(json.dumps({
                        "metric": "spmd_plane_latency",
                        "procs": n,
                        **row,
                    }))
                return 0
        sys.stderr.write("no RESULT line from coordinator\n"
                         + outs[0][-3000:] + "\n")
        return 1


if __name__ == "__main__":
    raise SystemExit(main())
