#!/usr/bin/env python
"""Measure the five BASELINE.md benchmark configs through the product
paths (PQL -> executor -> fused device dispatch), printing one JSON line
per config.

Run on the default backend (TPU when the axon relay is up, CPU
otherwise):

    PYTHONPATH=/root/repo:$PYTHONPATH python benchmarks/measure.py

Configs (BASELINE.md "North-star target"):
  1. single-shard Count(Intersect(Row,Row)) QPS
  2. Union/Intersect/Difference latency over a multi-shard set field
  3. TopN(n=100) with BSI Range filter, p50 latency
  4. GroupBy + Sum over BSI int fields, p50 latency
  5. 3-node HTTP cluster Count QPS (scatter-gather over the wire)

Shapes scale DOWN off-TPU so the script stays interactive; the recorded
BASELINE.md numbers come from TPU runs at the stated shapes.
"""

from __future__ import annotations

import json
import os
import random
import shutil
import statistics
import sys
import tempfile
import time

import numpy as np


def _now() -> float:
    return time.perf_counter()


def timed_qps(fn, min_iters: int = 20, min_time: float = 1.0):
    fn()  # warm-up / compile
    iters, t0 = 0, _now()
    while iters < min_iters or _now() - t0 < min_time:
        fn()
        iters += 1
    return iters / (_now() - t0)


def timed_qps_spread(fn, runs: int = 3, min_iters: int = 10,
                     min_time: float = 5.0) -> dict:
    """Closed-loop QPS with a variance bound: ``runs`` independent
    minimum-duration loops, reporting the median, every run, the
    run-to-run spread, and per-request p50/p95 latency.  One-shot
    unpinned loops drifted 186->404 QPS between round-2 runs (VERDICT
    round-2 weak #1) — a recorded figure needs its spread."""
    fn()  # warm-up / compile / connection establishment
    qps_runs: list[float] = []
    lats: list[float] = []
    for _ in range(runs):
        iters, t0 = 0, _now()
        while iters < min_iters or _now() - t0 < min_time:
            t1 = _now()
            fn()
            lats.append(_now() - t1)
            iters += 1
        qps_runs.append(iters / (_now() - t0))
    med = statistics.median(qps_runs)
    lats.sort()
    return {
        "value": round(med, 1),
        "runs": [round(q, 1) for q in qps_runs],
        "spread_pct": round((max(qps_runs) - min(qps_runs)) / med * 100, 1),
        "p50_ms": round(lats[len(lats) // 2] * 1e3, 2),
        "p95_ms": round(lats[min(len(lats) - 1, int(len(lats) * 0.95))] * 1e3,
                        2),
    }


def timed_p50_ms(fn, iters: int = 30):
    fn()  # warm-up / compile
    samples = []
    for _ in range(iters):
        t0 = _now()
        fn()
        samples.append((_now() - t0) * 1e3)
    return statistics.median(samples)


def build_index(holder, name: str, n_shards: int, rows_per_field: int,
                density_cols: int, seed: int):
    """An index with two set fields (f, g), an int field (v) and a
    time-quantum field (t), populated across n_shards shards."""
    from pilosa_tpu.models.field import FieldOptions
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    idx = holder.create_index(name)
    rng = random.Random(seed)
    for fname in ("f", "g"):
        f = idx.create_field(fname)
        rows, cols = [], []
        for row in range(rows_per_field):
            for _ in range(density_cols):
                s = rng.randrange(n_shards)
                cols.append(s * SHARD_WIDTH + rng.randrange(SHARD_WIDTH))
                rows.append(row)
        f.import_bits(rows, cols)
    v = idx.create_field("v", FieldOptions.int_field(0, 1 << 20))
    vcols = sorted({s * SHARD_WIDTH + rng.randrange(SHARD_WIDTH)
                    for s in range(n_shards) for _ in range(density_cols)})
    v.import_values(vcols, [rng.randrange(1 << 20) for _ in vcols])
    from pilosa_tpu.models.timequantum import parse_time

    t = idx.create_field("t", FieldOptions.time_field("YMDH"))
    trows, tcols, times = [], [], []
    for row in range(4):
        for _ in range(density_cols):
            s = rng.randrange(n_shards)
            trows.append(row)
            tcols.append(s * SHARD_WIDTH + rng.randrange(SHARD_WIDTH))
            times.append(parse_time(
                f"2019-0{1 + rng.randrange(9)}-15T0{rng.randrange(10)}:00"))
    t.import_bits(trows, tcols, timestamps=times)
    return idx


#: device configs need at least this host->device bandwidth; any real
#: TPU host's DMA clears it by 10-100x, while the axon relay tunnel
#: (observed ~MB/s, wedges on multi-GB transfers) never does
MIN_DEVICE_GBPS = 0.05


class _ConfigSkip(Exception):
    """One config declines to produce a number; the sweep records the
    reason and continues (no silent shrink, no dead artifact)."""


def main():
    from pilosa_tpu import axon_guard

    axon_guard.guard_dead_relay()
    import jax

    tunnel_note = None
    if (os.environ.get("PALLAS_AXON_POOL_IPS")
            and jax.config.jax_platforms != "cpu"):
        # tunneled chip: measure what the relay can actually move
        # BEFORE the in-process backend initializes, and pin the sweep
        # to the host engine when the working sets could never transfer
        # in a sane window (the 10B config's prewarm pushes ~2.5 GB).
        # Round 4: staging is CHUNKED (bitmap.chunked_device_put, 16 MB
        # pieces through a tunnel via PILOSA_TPU_STAGE_CHUNK_MB), so a
        # slow-but-alive tunnel no longer wedges mid-transfer — above
        # the floor the 1B config's ~0.3 GB stacks move on-chip in
        # seconds; the floor still protects the sweep's wall clock
        gbps = axon_guard.measured_transfer_gbps()
        if gbps >= MIN_DEVICE_GBPS:
            # bound any single tunnel transfer well under the wedge
            # threshold; real hosts ignore this (chunking is disabled
            # by default outside tunneled entry points)
            os.environ.setdefault("PILOSA_TPU_STAGE_CHUNK_MB", "16")
        else:
            tunnel_note = {
                "config": "device-sweep", "skipped": True,
                "reason": f"tunnel transfer bandwidth {gbps:.4f} GB/s "
                          f"< {MIN_DEVICE_GBPS} GB/s floor; sweep runs "
                          f"host-engine (exact results, CPU timings); "
                          f"chip headline lives in bench.py's smaller "
                          f"working set"}
            jax.config.update("jax_platforms", "cpu")

    on_tpu = jax.devices()[0].platform == "tpu"
    n_shards = 64 if on_tpu else 16
    rows_per_field = 512 if on_tpu else 64
    density = 4096 if on_tpu else 512

    from pilosa_tpu.models.holder import Holder
    from pilosa_tpu.parallel.executor import Executor
    from pilosa_tpu.shardwidth import SHARD_WIDTH

    out = []
    if tunnel_note is not None:
        out.append(tunnel_note)

    bench_dir = tempfile.mkdtemp()
    holder = Holder(bench_dir + "/bench")
    build_index(holder, "b", n_shards, rows_per_field, density, seed=1)
    ex = Executor(holder)

    # ---- config 1: single-shard Count(Intersect) QPS
    q1 = "Count(Intersect(Row(f=1), Row(g=2)))"
    qps1 = timed_qps(lambda: ex.execute("b", q1, shards=[0]))
    out.append({"config": 1, "metric": "intersect_count_qps_1shard",
                "value": round(qps1, 1), "unit": "qps"})

    # ---- config 2: multi-shard set algebra latency
    q2 = "Count(Union(Row(f=1), Intersect(Row(f=2), Row(g=3)), Difference(Row(f=4), Row(g=5))))"
    p2 = timed_p50_ms(lambda: ex.execute("b", q2))
    out.append({"config": 2, "metric": "set_algebra_p50_ms",
                "value": round(p2, 2), "unit": "ms",
                "cols": n_shards * SHARD_WIDTH})

    # ---- config 2b: ≥1B-column index through the product path, with
    # the residency manager under genuine pressure.  1024 shards at the
    # default 2^20 shard width = 1.07B columns; each row stack is a
    # [1024, 32768] uint32 (128 MiB), and the budget below holds ~3 of
    # them, so cycling 6 rows evicts constantly while every count must
    # stay exact (the two-tier residency design of SURVEY.md §7's risk
    # register: eviction may cost warmth, never correctness).
    from pilosa_tpu.runtime import residency

    scale_shards = max(1024, -(-(1 << 30) // SHARD_WIDTH))  # >= 1.07B cols
    scale_cols = scale_shards * SHARD_WIDTH
    srng = random.Random(7)
    scale_bits: dict[int, set] = {}
    sidx = holder.create_index("scale")
    sf = sidx.create_field("f")
    rows_l: list[int] = []
    cols_l: list[int] = []
    prev: list[int] = []
    for row in range(6):
        cs = [srng.randrange(scale_cols) for _ in range(30_000)]
        cs += prev[:6_000]  # overlap with the previous row
        prev = cs
        scale_bits[row] = set(cs)
        rows_l += [row] * len(cs)
        cols_l += cs
    sf.import_bits(rows_l, cols_l)

    stack_bytes = scale_shards * (SHARD_WIDTH // 8)
    # shrink the budget on the LIVE manager: a reset() would orphan the
    # entries configs 1-2 already admitted (they would become untracked
    # and unevictable for the rest of the run)
    mgr = residency.manager()
    old_budget = mgr.budget
    old_sized = mgr.operator_sized
    mgr.budget = 3 * stack_bytes + stack_bytes // 2
    mgr.operator_sized = True
    try:
        ev0 = mgr.evictions
        lat = []
        for i in range(8):
            a, b = i % 5, i % 5 + 1
            t0 = _now()
            got = ex.execute("scale", f"Count(Intersect(Row(f={a}), Row(f={b})))")[0]
            lat.append((_now() - t0) * 1e3)
            want = len(scale_bits[a] & scale_bits[b])
            assert got == want, f"scale mismatch r{a}&r{b}: {got} != {want}"
        evictions = mgr.evictions - ev0
        assert evictions > 0, "budget never forced an eviction — not a thrash run"
    finally:
        # restore BOTH knobs for the configs below: a leaked
        # operator_sized=True relaxes per-entry cache caps to budget//4
        # and would silently change configs 3-5's caching policy
        mgr.budget = old_budget
        mgr.operator_sized = old_sized
    out.append({"config": 2, "metric": "intersect_count_p50_ms_1B_cols",
                "value": round(statistics.median(lat), 1), "unit": "ms",
                "cols": scale_cols, "evictions": evictions,
                "exact": True})
    holder.delete_index("scale")

    # ---- config 2c: the 10B-column north star (BASELINE.md target
    # shape), end-to-end through the product path.  9,537 shards at the
    # default width = 10.0B columns; each row stack is ~1.25 GB, so this
    # config is gated on available host memory (it needs ~8 GB headroom)
    # and runs the query loop at full scale.
    avail_kb = 0
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable"):
                    avail_kb = int(line.split()[1])
                    break
    except OSError:
        pass
    if avail_kb >= 16 * 1024 * 1024 and SHARD_WIDTH >= (1 << 20):
        ns_shards = -(-(10 * 10**9) // SHARD_WIDTH)  # ceil -> >= 10B cols
        ns_cols = ns_shards * SHARD_WIDTH
        nrng = random.Random(10)
        nidx = holder.create_index("northstar")
        nf = nidx.create_field("f")
        nbits: dict[int, set] = {0: set(), 1: set()}
        rows_l, cols_l = [], []
        for row in (0, 1):
            # >= 2 bits in EVERY shard so all 9,537 fragments exist,
            # plus a dense overlap slice so the intersection is nonzero
            for s in range(ns_shards):
                for _ in range(2):
                    c = s * SHARD_WIDTH + nrng.randrange(SHARD_WIDTH)
                    nbits[row].add(c)
                    rows_l.append(row)
                    cols_l.append(c)
        shared = [nrng.randrange(ns_cols) for _ in range(5_000)]
        for row in (0, 1):
            for c in shared:
                if c not in nbits[row]:
                    nbits[row].add(c)
                    rows_l.append(row)
                    cols_l.append(c)
        # a deployment serving a 10B-column index sizes its memory for
        # the working set (two ~1.25 GB row stacks) BEFORE loading —
        # the budget must be in place when the import-triggered prewarm
        # runs, or it gates itself off
        mgr10 = residency.manager()
        old10 = mgr10.budget
        old10_sized = mgr10.operator_sized
        mgr10.budget = max(old10, 8 << 30)
        mgr10.operator_sized = True
        try:
            t0 = _now()
            nf.import_bits(rows_l, cols_l)
            import_s = _now() - t0
            # the import queued a background stack prewarm; wait it out
            # so "cold_ms" below measures what a first query actually
            # sees on a settled server (prewarm.py).  The un-prewarmed
            # floor is measured separately after the warm loop.
            from pilosa_tpu.runtime import prewarm, snapqueue

            t0 = _now()
            if not (prewarm.drain(timeout=300.0)
                    and snapqueue.drain(timeout=300.0)):
                # never crash the sweep: a drain that can't settle
                # (e.g. device transfers crawling through a thin
                # tunnel) becomes a skip record, not a dead artifact
                raise _ConfigSkip("background prewarm/compaction did "
                                  "not settle in 300 s")
            prewarm_s = _now() - t0
            q_ns = "Count(Intersect(Row(f=0), Row(f=1)))"
            t0 = _now()
            got = ex.execute("northstar", q_ns)[0]
            cold_ms = (_now() - t0) * 1e3
            lat = []
            for _ in range(3):
                t0 = _now()
                got = ex.execute("northstar", q_ns)[0]
                lat.append((_now() - t0) * 1e3)
            # TopN p50 at the north-star scale (BASELINE.json tracks
            # it alongside the count): the full stacked row scan over
            # the same warm 10B-column stacks, exact counts asserted
            tn_lat = []
            for _ in range(3):
                t0 = _now()
                pairs = ex.execute("northstar", "TopN(f)")[0]
                tn_lat.append((_now() - t0) * 1e3)
            got_tn = [(p.id, p.count) for p in pairs]
            want_tn = sorted(
                ((r, len(nbits[r])) for r in (0, 1)),
                key=lambda rc: (-rc[1], rc[0]))
            # verified in the else-branch below, where a mismatch
            # becomes a loud correctness_failure record instead of an
            # AssertionError that kills the whole sweep
            # AE leg at the north star: one block-checksum-only pass
            # over every 10B fragment — the per-cycle hashing floor a
            # holderSyncer pays before any wire traffic (reference
            # holder.go:880, fragment.go:1762 Checksum; cadence 10 min,
            # server.go:514)
            t0 = _now()
            ae_blocks = 0
            ae_bytes = 0
            for vw in nf.views.values():
                for fr in vw.fragments.values():
                    ae_blocks += len(fr.blocks())
                    ae_bytes += sum(fr._rows[r].nbytes
                                    for r in fr.row_ids()) + 8 * len(
                                        fr.row_ids())
            ae_checksum_s = _now() - t0
            # documented floor: evict the row stacks and pay the full
            # assembly on a quiet system (no compaction running) — what
            # a query sees if eviction or a disabled prewarm leaves it
            # cold
            for key in list(nf._row_stack_cache):
                residency.manager().forget(nf._row_stack_cache, key)
            nf._row_stack_cache.clear()
            t0 = _now()
            got_floor = ex.execute("northstar", q_ns)[0]
            floor_ms = (_now() - t0) * 1e3
        except _ConfigSkip as e:
            out.append({"config": 2,
                        "metric": "intersect_count_p50_ms_10B_cols",
                        "skipped": True, "reason": str(e)})
            holder.delete_index("northstar")
        else:
            # A correctness mismatch must be LOUD but must not kill the
            # sweep: configs 1-2's collected numbers and configs 3-5
            # still have to reach the artifact, and the ~2.5 GB index
            # still has to be deleted — so the violation becomes an
            # explicit correctness_failure record, never a dead run
            # (the same doctrine as the skip records above).
            want = len(nbits[0] & nbits[1])
            failures = []
            if got != want:
                failures.append(f"north-star count {got} != {want}")
            if got_floor != want:
                failures.append(f"floor count {got_floor} != {want}")
            if got_tn != want_tn:
                failures.append(f"TopN {got_tn} != {want_tn}")
            if failures:
                out.append({"config": 2,
                            "metric": "intersect_count_p50_ms_10B_cols",
                            "correctness_failure": "; ".join(failures)})
            else:
                out.append({
                    "config": 2,
                    "metric": "intersect_count_p50_ms_10B_cols",
                    "value": round(statistics.median(lat), 1),
                    "unit": "ms",
                    "cols": ns_cols, "shards": ns_shards,
                    "cold_ms": round(cold_ms, 1),
                    "prewarm_s": round(prewarm_s, 1),
                    "cold_floor_no_prewarm_ms": round(floor_ms, 1),
                    "topn_p50_ms": round(statistics.median(tn_lat), 1),
                    "import_s": round(import_s, 1), "exact": True})
                out.append({
                    "config": 7,
                    "metric": "ae_checksum_pass_s_10B_cols",
                    "value": round(ae_checksum_s, 2), "unit": "s",
                    "cols": ns_cols, "shards": ns_shards,
                    "blocks": ae_blocks,
                    "mb_hashed": round(ae_bytes / 1e6, 1)})
            holder.delete_index("northstar")
        finally:
            mgr10.budget = old10
            mgr10.operator_sized = old10_sized
    else:
        # a gated config must leave a record, never silently shrink the
        # artifact (VERDICT round-2 weak #6)
        reasons = []
        if avail_kb < 16 * 1024 * 1024:
            reasons.append(f"MemAvailable {avail_kb / (1 << 20):.1f} GiB "
                           f"< 16 GiB required")
        if SHARD_WIDTH < (1 << 20):
            reasons.append(f"SHARD_WIDTH {SHARD_WIDTH} < 2^20 (bench shape "
                           f"assumes default width)")
        out.append({"config": 2, "metric": "intersect_count_p50_ms_10B_cols",
                    "skipped": True, "reason": "; ".join(reasons)})

    # ---- config 3: TopN(n=100) with BSI range filter p50
    q3 = "TopN(f, Row(v > 524288), n=100)"
    p3 = timed_p50_ms(lambda: ex.execute("b", q3))
    out.append({"config": 3, "metric": "topn_bsi_filter_p50_ms",
                "value": round(p3, 2), "unit": "ms",
                "rows": rows_per_field})
    # time-quantum range form
    q3b = "TopN(t, n=100)"
    p3b = timed_p50_ms(lambda: ex.execute("b", q3b))
    out.append({"config": 3, "metric": "topn_time_field_p50_ms",
                "value": round(p3b, 2), "unit": "ms"})

    # ---- config 4: GroupBy + Sum p50
    q4 = "GroupBy(Rows(f), Rows(g), filter=Row(v > 262144))"
    # cap the walk: rows_per_field^2 groups is the worst case
    p4 = timed_p50_ms(lambda: ex.execute("b", q4, shards=None), iters=10)
    out.append({"config": 4, "metric": "groupby_filtered_p50_ms",
                "value": round(p4, 2), "unit": "ms",
                "groups_max": rows_per_field * rows_per_field})
    q4b = "Sum(Row(f=1), field=v)"
    p4b = timed_p50_ms(lambda: ex.execute("b", q4b))
    out.append({"config": 4, "metric": "sum_filtered_p50_ms",
                "value": round(p4b, 2), "unit": "ms"})

    holder.close()
    # Quiesce before the latency benchmark: the scale configs above
    # wrote multi-GB of snapshots whose dirty pages would otherwise
    # write back DURING config 5's closed loop and collapse a run on a
    # one-core box (observed: 442 -> 12.7 QPS across runs).  Deleting
    # the tree drops the dirty pages instead of flushing them; sync
    # settles whatever remains.
    shutil.rmtree(bench_dir, ignore_errors=True)
    os.sync()

    # ---- config 5: 3-node HTTP cluster Count QPS
    from pilosa_tpu.server.client import InternalClient
    from pilosa_tpu.server.server import Server

    base = tempfile.mkdtemp()
    s0 = Server(data_dir=f"{base}/n0", coordinator=True); s0.open()
    s1 = Server(data_dir=f"{base}/n1", seeds=[s0.uri]); s1.open()
    s2 = Server(data_dir=f"{base}/n2", seeds=[s0.uri]); s2.open()

    # a keep-alive client, like any real driver (and the reference's
    # closed-loop benchmark clients)
    client = InternalClient(timeout=120)

    def post(path, obj):
        return client.post_json(s0.uri + path, obj)

    post("/index/c", {})
    post("/index/c/field/f", {})
    rng = random.Random(2)
    rows, cols = [], []
    for row in range(8):
        for _ in range(density):
            s = rng.randrange(9)
            rows.append(row)
            cols.append(s * SHARD_WIDTH + rng.randrange(SHARD_WIDTH))
    post("/index/c/field/f/import", {"rowIDs": rows, "columnIDs": cols})
    q5 = {"query": "Count(Intersect(Row(f=1), Row(f=2)))"}
    spread5 = timed_qps_spread(lambda: post("/index/c/query", q5))
    out.append({"config": 5, "metric": "cluster3_count_qps_http",
                "unit": "qps", **spread5})

    # ---- config 6: write path — single-Set latency and bulk-import
    # throughput (the reference's headline ingest paths: executeSet,
    # executor.go:2067, and fragment.bulkImport, fragment.go:1997).
    # Reuses the 3-node cluster: every Set replicates synchronously to
    # all shard owners, so this measures the real write pipeline (WAL
    # append + replica POST), not a single-map update.
    rng6 = random.Random(6)
    set_lat = []
    for i in range(300):
        col = rng6.randrange(9 * SHARD_WIDTH)
        q = {"query": f"Set({col}, f={100 + (i % 8)})"}
        t0 = _now()
        post("/index/c/query", q)
        set_lat.append((_now() - t0) * 1e3)
    set_lat.sort()
    out.append({"config": 6, "metric": "set_write_p50_ms_replicated",
                "value": round(set_lat[len(set_lat) // 2], 2),
                "unit": "ms",
                "p95_ms": round(set_lat[int(len(set_lat) * 0.95)], 2),
                "writes": len(set_lat)})

    n_bits = 2_000_000
    rows6 = [rng6.randrange(64) for _ in range(n_bits)]
    cols6 = [rng6.randrange(9 * SHARD_WIDTH) for _ in range(n_bits)]
    t0 = _now()
    post("/index/c/field/f/import", {"rowIDs": rows6,
                                     "columnIDs": cols6})
    dt = _now() - t0
    got6 = post("/index/c/query",
                {"query": "Count(Union(" + ", ".join(
                    f"Row(f={r})" for r in range(8)) + "))"})["results"][0]
    # exact oracle over everything this sweep put into rows 0-7: the
    # config-5 import plus this bulk import (Set() wrote rows 100-107)
    want6_set = ({c for r, c in zip(rows, cols) if r < 8}
                 | {c for r, c in zip(rows6, cols6) if r < 8})
    want6 = len(want6_set)
    rec6 = {"config": 6, "metric": "bulk_import_mbits_per_s_json",
            "value": round(n_bits / dt / 1e6, 2),
            "unit": "Mbits/s", "bits": n_bits,
            "wall_s": round(dt, 1), "exact": got6 == want6}
    if got6 != want6:
        rec6["correctness_failure"] = f"union count {got6} != {want6}"
    out.append(rec6)

    # Same bulk import over the protobuf wire form (the reference's
    # CSV importer posts ImportRequest protobufs, ctl/import.go:34-350;
    # the JSON figure above is dominated by 2M-element JSON encoding)
    from pilosa_tpu import proto as _proto

    rows6b = [rng6.randrange(64) for _ in range(n_bits)]
    cols6b = [rng6.randrange(9 * SHARD_WIDTH) for _ in range(n_bits)]
    body6 = _proto.encode(_proto.IMPORT_REQUEST, {
        "index": "c", "field": "f", "shard": 0,
        "rowIDs": rows6b, "columnIDs": cols6b})
    t0 = _now()
    client._request(
        "POST", s0.uri + "/index/c/field/f/import", body6,
        ctype="application/x-protobuf")
    dtb = _now() - t0
    got6b = post("/index/c/query",
                 {"query": "Count(Union(" + ", ".join(
                     f"Row(f={r})" for r in range(8)) + "))"})["results"][0]
    want6b = len(want6_set | {c for r, c in zip(rows6b, cols6b) if r < 8})
    rec6b = {"config": 6, "metric": "bulk_import_mbits_per_s_proto",
             "value": round(n_bits / dtb / 1e6, 2),
             "unit": "Mbits/s", "bits": n_bits,
             "wall_s": round(dtb, 1), "exact": got6b == want6b}
    if got6b != want6b:
        rec6b["correctness_failure"] = f"union count {got6b} != {want6b}"
    out.append(rec6b)

    # The import-roaring fast path (reference api.go:368 ImportRoaring
    # -> roaring.ImportRoaringBits, roaring/roaring.go:1511 — its
    # fastest ingest).  Payloads are PRE-ENCODED per shard (matching
    # the reference benchmark shape: the server-side rate is what's
    # measured).  Two densities: the protobuf row's sparse 2M-random
    # shape (worst case for bitmap merge — ~1 bit per 64-bit word),
    # and a 10x-denser bulk-load shape where container merges amortize.
    from pilosa_tpu.storage import roaring as _rcodec

    for label, nb, row0 in (("sparse", n_bits, 200),
                            ("dense", 10 * n_bits, 300)):
        rng_r = np.random.default_rng(7 + nb)
        rows_r = rng_r.integers(row0, row0 + 64, nb, dtype=np.int64)
        cols_r = rng_r.integers(0, 9 * SHARD_WIDTH, nb, dtype=np.int64)
        shard_r = cols_r // SHARD_WIDTH
        pos_r = (rows_r * SHARD_WIDTH
                 + (cols_r % SHARD_WIDTH)).astype(np.uint64)
        payloads = {}
        uniq_total = 0
        for s in range(9):
            u = np.unique(pos_r[shard_r == s])
            uniq_total += len(u)
            k_, w_ = _rcodec.positions_to_containers(u)
            payloads[s] = _rcodec.encode(k_, w_)
        wire_b = sum(len(v) for v in payloads.values())
        t0 = _now()
        for s, data in payloads.items():
            client.import_roaring(s0.uri, "c", "f", s, data)
        dtr = _now() - t0
        got_r = post("/index/c/query", {"query": "Count(Union("
                     + ", ".join(f"Row(f={r})"
                                 for r in range(row0, row0 + 64))
                     + "))"})["results"][0]
        want_r = len(np.unique(cols_r))
        rec_r = {"config": 6,
                 "metric": f"import_roaring_mbits_per_s_{label}",
                 "value": round(uniq_total / dtr / 1e6, 2),
                 "unit": "Mbits/s", "bits": uniq_total,
                 "wire_mb_per_s": round(wire_b / dtr / 1e6, 1),
                 "wall_s": round(dtr, 2), "exact": got_r == want_r}
        if got_r != want_r:
            rec_r["correctness_failure"] = \
                f"union count {got_r} != {want_r}"
        out.append(rec_r)

    client.close()
    s0.close(); s1.close(); s2.close()

    # ---- config 7: anti-entropy cycle cost at scale (VERDICT r4 item
    # 4; reference holderSyncer holder.go:880-1101, 10-min cadence
    # server.go:514).  Fresh replica-2 cluster so blocks actually have
    # two owners; AE loops disabled — cycles run by hand, timed.
    # Leg A: in-sync full SyncHolder cycle over a wide index (wall +
    #   bytes hashed: the steady-state cost of "nothing to do").
    # Leg B: one replica diverges (direct local import bypassing
    #   replication); the next cycle must move ONLY the diff and every
    #   node must answer exactly afterwards.
    ae_shards = 1024 if avail_kb >= 8 * 1024 * 1024 else 128
    base7 = tempfile.mkdtemp()
    a0 = Server(data_dir=f"{base7}/n0", coordinator=True, replica_n=2)
    a0.open()
    a1 = Server(data_dir=f"{base7}/n1", seeds=[a0.uri], replica_n=2)
    a1.open()
    a2 = Server(data_dir=f"{base7}/n2", seeds=[a0.uri], replica_n=2)
    a2.open()
    cl7 = InternalClient(timeout=300)

    def post7(path, obj):
        return cl7.post_json(a0.uri + path, obj)

    # replica-2 writes need all three members up before the import; a
    # cluster that never forms becomes a skip record, never a run
    # against a partial cluster (which would record false divergence)
    deadline = _now() + 120
    ready = False
    while _now() < deadline:
        st = cl7._json("GET", a0.uri + "/status")
        if st.get("state") == "NORMAL" and len(st.get("nodes", [])) == 3:
            ready = True
            break
        time.sleep(0.2)
    if not ready:
        out.append({"config": 7, "metric": "ae_sync_cycle_s_insync",
                    "skipped": True,
                    "reason": "3-node replica-2 cluster never reached "
                              "NORMAL within 120 s"})
        cl7.close()
        a0.close(); a1.close(); a2.close()
        shutil.rmtree(base7, ignore_errors=True)
        return _emit(out)

    post7("/index/ae", {})
    post7("/index/ae/field/f", {})
    arng = random.Random(77)
    rows_l, cols_l = [], []
    for row in range(4):
        for s in range(ae_shards):
            for _ in range(2):
                rows_l.append(row)
                cols_l.append(s * SHARD_WIDTH + arng.randrange(SHARD_WIDTH))
    post7("/index/ae/field/f/import", {"rowIDs": rows_l,
                                       "columnIDs": cols_l})

    from pilosa_tpu.parallel.syncer import HolderSyncer

    def hashed_mb(server):
        total = 0
        idx = server.holder.index("ae")
        for f in idx.all_fields():
            for vw in f.views.values():
                for fr in vw.fragments.values():
                    if server.cluster.owns_shard(
                            server.cluster.local_id, "ae", fr.shard):
                        total += sum(fr._rows[r].nbytes
                                     for r in fr.row_ids())
        return total / 1e6

    t0 = _now()
    dirty_a = HolderSyncer(a0.node).sync_holder()
    wall_a = _now() - t0
    rec7 = {"config": 7, "metric": "ae_sync_cycle_s_insync",
            "value": round(wall_a, 2), "unit": "s",
            "cols": ae_shards * SHARD_WIDTH, "shards": ae_shards,
            "dirty_blocks": dirty_a,
            "local_mb_hashed": round(hashed_mb(a0), 1)}
    if dirty_a:
        rec7["correctness_failure"] = \
            f"{dirty_a} dirty blocks on an in-sync cluster"
    out.append(rec7)

    # Leg B — diverge one replica: bits land on a1 only (local import,
    # no replication), on shards a1 owns; AE must push them everywhere.
    div_shards = [s for s in range(ae_shards)
                  if a1.cluster.owns_shard(a1.cluster.local_id, "ae", s)][:8]
    div_want = 0
    for s in div_shards:
        frag = a1.node.local_fragment("ae", "f", "standard", s, True)
        frag.import_positions(
            [9 * SHARD_WIDTH + arng.randrange(SHARD_WIDTH)
             for _ in range(125)])
        div_want += frag.row_count(9)
    t0 = _now()
    dirty_b = HolderSyncer(a1.node).sync_holder()
    wall_b = _now() - t0
    got_counts = []
    for srv in (a0, a1, a2):
        got_counts.append(cl7.post_json(
            srv.uri + "/index/ae/query",
            {"query": "Count(Row(f=9))"})["results"][0])
    rec7b = {"config": 7, "metric": "ae_sync_cycle_s_diverged",
             "value": round(wall_b, 2), "unit": "s",
             "diverged_shards": len(div_shards),
             "diverged_bits": div_want,
             "dirty_blocks": dirty_b,
             "exact": all(g == div_want for g in got_counts)}
    if not rec7b["exact"]:
        rec7b["correctness_failure"] = \
            f"post-AE counts {got_counts} != {div_want}"
    out.append(rec7b)

    cl7.close()
    a0.close(); a1.close(); a2.close()
    shutil.rmtree(base7, ignore_errors=True)

    return _emit(out)


def _emit(out):
    import jax

    platform = jax.devices()[0].platform
    for rec in out:
        rec["platform"] = platform
        print(json.dumps(rec))


if __name__ == "__main__":
    sys.exit(main())
