#!/usr/bin/env python
"""On-chip Pallas validation: run every Pallas kernel NON-interpreted on
the real TPU against its jnp/numpy oracle and record pass/fail.

CI exercises the kernels with interpret=True only (no chip in the test
environment), which cannot catch Mosaic lowering bugs — this script is
the relay-up-only complement (VERDICT round-1 weak #3).  Run whenever
the chip is reachable:

    PYTHONPATH=/root/repo:$PYTHONPATH python benchmarks/validate_tpu.py

Writes PALLAS_TPU_VALIDATION.json at the repo root: one entry per
kernel with ok/detail, plus the platform and device kind.  Exits 0 with
status "skipped" when no TPU is reachable (never blocks CI).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

from pilosa_tpu.axon_guard import guard_dead_relay

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "PALLAS_TPU_VALIDATION.json")


def main() -> int:
    guard_dead_relay()
    import jax

    from pilosa_tpu.ops import pallas_kernels as pk

    dev = jax.devices()[0]
    if dev.platform not in ("tpu", "axon"):
        # Never clobber a real chip result with a skip: a CPU-fallback
        # run during a relay outage must leave the last on-chip
        # validation in place (it is the committed evidence).
        try:
            prior = json.load(open(OUT))
        except (OSError, ValueError):
            prior = None
        if prior and prior.get("status") == "ran":
            print(f"skipped: platform={dev.platform}; keeping prior "
                  f"on-chip result ({prior.get('device_kind')})")
            return 0
        json.dump({"status": "skipped",
                   "reason": f"no TPU (platform={dev.platform})"},
                  open(OUT, "w"), indent=1)
        print(f"skipped: platform={dev.platform}")
        return 0

    rng = np.random.default_rng(12348)
    results = {}

    def check(name, fn):
        try:
            fn()
            results[name] = {"ok": True}
            print(f"PASS {name}")
        except Exception as e:
            results[name] = {"ok": False, "detail": f"{type(e).__name__}: {e}"}
            print(f"FAIL {name}: {e}")

    def words(*shape):
        return rng.integers(0, 1 << 32, size=shape, dtype=np.uint32)

    def _row_counts():
        mat, filt = words(300, 4096), words(4096)
        got = np.asarray(pk._row_counts_masked_pallas(mat, filt))
        want = np.bitwise_count(mat & filt).sum(axis=-1).astype(np.int32)
        np.testing.assert_array_equal(got, want)

    def _count_and():
        a, b = words(1 << 18), words(1 << 18)
        got = int(pk._count_and_pallas(a, b))
        want = int(np.bitwise_count(a & b).sum(dtype=np.uint64))
        assert got == want, (got, want)

    def _bsi_compare():
        depth = 21
        planes, filt = words(2 + depth, 8192), words(8192)
        upred = int(rng.integers(0, 1 << depth))
        # private Pallas entry, NOT the public wrapper: the wrapper
        # routes by committed winners, so after a winner='xla' capture
        # it would compare the jnp fallback against itself and record
        # a vacuous ok while a Mosaic regression hides
        import jax.numpy as jnp

        pred_masks = np.array(
            [[0xFFFFFFFF if (upred >> i) & 1 else 0]
             for i in range(depth)], dtype=np.uint32)
        lt, gt = pk._bsi_compare_pallas(
            jnp.asarray(planes), jnp.asarray(filt),
            jnp.asarray(pred_masks), depth)
        wlt, wgt = pk._bsi_compare_jnp(planes, filt, upred, depth)
        np.testing.assert_array_equal(np.asarray(lt), np.asarray(wlt))
        np.testing.assert_array_equal(np.asarray(gt), np.asarray(wgt))

    def _mmc():
        import jax.numpy as jnp

        mat, masks = words(200, 1024), words(17, 1024)
        got = np.asarray(pk._mmc_pallas(jnp.asarray(mat),
                                        jnp.asarray(masks)))
        want = np.bitwise_count(
            mat[None, :, :] & masks[:, None, :]).sum(axis=-1)
        np.testing.assert_array_equal(got, want.astype(np.int32))

    check("row_counts_masked", _row_counts)
    check("count_and", _count_and)
    check("bsi_compare_unsigned", _bsi_compare)
    check("masked_matrix_counts", _mmc)

    # --- per-kernel Pallas-vs-XLA timing at executor-realistic shapes —
    # the evidence that decides pallas_kernels.pallas_enabled defaults.
    # All operands are GENERATED ON DEVICE (jax.random.bits): the axon
    # tunnel moves host->device data at ~MB/s and wedges on big pushes,
    # so a timing pass must never stream operands through it.  Timing
    # rotates 8 distinct variants through a pipelined loop (block once),
    # median of 3 repeats — identical-dispatch loops are memoized
    # behind the relay and report fantasy numbers (see bench.py).
    import time

    import jax.numpy as jnp
    import jax.random as jr

    from pilosa_tpu.ops import bitmap as bm

    def timed_us(fn, variants, min_iters=16):
        outs = [fn(*v) for v in variants]
        jax.block_until_ready(outs)  # compile + warm every variant
        meds = []
        for _ in range(3):
            iters = max(min_iters, len(variants))
            t0 = time.perf_counter()
            outs = [fn(*variants[i % len(variants)])
                    for i in range(iters)]
            jax.block_until_ready(outs)
            meds.append((time.perf_counter() - t0) / iters)
        meds.sort()
        return meds[1] * 1e6

    def dvars(key, *shape, n=8):
        ks = jr.split(jr.PRNGKey(key), n)
        return [jr.bits(k, shape, dtype=jnp.uint32) for k in ks]

    # physics backstop for the memoized-dispatch trap (same fault
    # bench.py flags): these kernels are HBM-bound, so a per-call time
    # below streaming the operand bytes at the HBM roof means dispatches
    # were cache hits, not executions — the A/B is then recorded as
    # suspect instead of deciding routing defaults from fantasy numbers
    kind = (dev.device_kind or "").lower().replace(" ", "")
    peak_gbps = next((p for k, p in (("v5lite", 819.0), ("v6lite", 1640.0),
                                     ("v5p", 2765.0), ("v4", 1228.0))
                      if k in kind), None)

    def ab(name, pallas_fn, xla_fn, variants, bytes_per_call):
        if not results.get(name, {}).get("ok"):
            return
        try:
            p_us = timed_us(pallas_fn, variants)
            x_us = timed_us(xla_fn, variants)
            perf = {
                "pallas_us": round(p_us, 1),
                "xla_us": round(x_us, 1),
                "winner": "pallas" if p_us < x_us else "xla",
            }
            if peak_gbps is not None:
                floor_us = bytes_per_call / (peak_gbps * 1e9) * 1e6
                if min(p_us, x_us) < floor_us:
                    perf["suspect_memoized_dispatch"] = True
                    perf["hbm_floor_us"] = round(floor_us, 1)
            results[name]["perf"] = perf
            print(f"PERF {name}: pallas {p_us:.0f} us vs xla "
                  f"{x_us:.0f} us -> {perf['winner']}"
                  + (" [SUSPECT: beat the HBM roof]"
                     if perf.get("suspect_memoized_dispatch") else ""))
        except Exception as e:  # noqa: BLE001 — perf is best-effort
            results[name]["perf"] = {"error": f"{type(e).__name__}: {e}"}
            print(f"PERF {name} failed: {e}")

    W = 32768  # one 2^20-column shard in uint32 words
    filt = dvars(99, W, n=1)[0]
    masks = dvars(98, 32, W, n=1)[0]
    planes_depth = 21

    ab("row_counts_masked",
       lambda m: pk._row_counts_masked_pallas(m, filt),
       lambda m: bm.row_counts_masked(m, filt),
       [(v,) for v in dvars(1, 512, W)],
       bytes_per_call=512 * W * 4)
    # count_and at the bench shape (256 shards' worth of words) — the
    # north-star op streams the full stacked operand pair
    b_flat = dvars(97, 256 * W, n=1)[0]
    ab("count_and",
       lambda a: pk._count_and_pallas(a, b_flat),
       lambda a: bm.popcount_and(a, b_flat),
       [(v,) for v in dvars(2, 256 * W)],
       bytes_per_call=2 * 256 * W * 4)
    # call the private kernel, NOT the public dispatcher — the
    # dispatcher consults pallas_enabled()/on_tpu(), so with the knob
    # off both legs would silently time XLA and record a meaningless
    # "winner" in the committed evidence
    pred_masks = jnp.asarray(np.array(
        [[0xFFFFFFFF if (123456 >> i) & 1 else 0]
         for i in range(planes_depth)], dtype=np.uint32))
    ab("bsi_compare_unsigned",
       lambda p: pk._bsi_compare_pallas(p, filt, pred_masks,
                                        planes_depth),
       lambda p: pk._bsi_compare_jnp(p, filt, 123456, planes_depth),
       [(v,) for v in dvars(3, 2 + planes_depth, W)],
       bytes_per_call=(2 + planes_depth) * W * 4)
    # the XLA leg must be the dispatcher's REAL fallback
    # (bm.masked_matrix_counts -> lax.map of fused row counts), not a
    # hand-rolled broadcast — routing evidence against code that never
    # runs in production would decide nothing
    ab("masked_matrix_counts",
       lambda m: pk._mmc_pallas(m, masks),
       lambda m: bm.masked_matrix_counts(m, masks),
       [(v,) for v in dvars(4, 512, W)],
       # true lower bound: each operand streamed once with perfect
       # VMEM reuse of the mask block
       bytes_per_call=(512 + 32) * W * 4)


    payload = {
        "status": "ran",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "kernels": results,
        "all_ok": all(r["ok"] for r in results.values()),
    }
    json.dump(payload, open(OUT, "w"), indent=1)
    print(json.dumps({"all_ok": payload["all_ok"]}))
    return 0 if payload["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
