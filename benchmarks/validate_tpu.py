#!/usr/bin/env python
"""On-chip Pallas validation: run every Pallas kernel NON-interpreted on
the real TPU against its jnp/numpy oracle and record pass/fail.

CI exercises the kernels with interpret=True only (no chip in the test
environment), which cannot catch Mosaic lowering bugs — this script is
the relay-up-only complement (VERDICT round-1 weak #3).  Run whenever
the chip is reachable:

    PYTHONPATH=/root/repo:$PYTHONPATH python benchmarks/validate_tpu.py

Writes PALLAS_TPU_VALIDATION.json at the repo root: one entry per
kernel with ok/detail, plus the platform and device kind.  Exits 0 with
status "skipped" when no TPU is reachable (never blocks CI).
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

from pilosa_tpu.axon_guard import guard_dead_relay

OUT = os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                   "PALLAS_TPU_VALIDATION.json")


def main() -> int:
    guard_dead_relay()
    import jax

    from pilosa_tpu.ops import pallas_kernels as pk

    dev = jax.devices()[0]
    if dev.platform not in ("tpu", "axon"):
        json.dump({"status": "skipped",
                   "reason": f"no TPU (platform={dev.platform})"},
                  open(OUT, "w"), indent=1)
        print(f"skipped: platform={dev.platform}")
        return 0

    rng = np.random.default_rng(12348)
    results = {}

    def check(name, fn):
        try:
            fn()
            results[name] = {"ok": True}
            print(f"PASS {name}")
        except Exception as e:
            results[name] = {"ok": False, "detail": f"{type(e).__name__}: {e}"}
            print(f"FAIL {name}: {e}")

    def words(*shape):
        return rng.integers(0, 1 << 32, size=shape, dtype=np.uint32)

    def _row_counts():
        mat, filt = words(300, 4096), words(4096)
        got = np.asarray(pk._row_counts_masked_pallas(mat, filt))
        want = np.bitwise_count(mat & filt).sum(axis=-1).astype(np.int32)
        np.testing.assert_array_equal(got, want)

    def _count_and():
        a, b = words(1 << 18), words(1 << 18)
        got = int(pk._count_and_pallas(a, b))
        want = int(np.bitwise_count(a & b).sum(dtype=np.uint64))
        assert got == want, (got, want)

    def _bsi_compare():
        depth = 21
        planes, filt = words(2 + depth, 8192), words(8192)
        upred = int(rng.integers(0, 1 << depth))
        lt, gt = pk.bsi_compare_unsigned(planes, filt, upred, depth)
        wlt, wgt = pk._bsi_compare_jnp(planes, filt, upred, depth)
        np.testing.assert_array_equal(np.asarray(lt), np.asarray(wlt))
        np.testing.assert_array_equal(np.asarray(gt), np.asarray(wgt))

    def _mmc():
        import jax.numpy as jnp

        mat, masks = words(200, 1024), words(17, 1024)
        got = np.asarray(pk._mmc_pallas(jnp.asarray(mat),
                                        jnp.asarray(masks)))
        want = np.bitwise_count(
            mat[None, :, :] & masks[:, None, :]).sum(axis=-1)
        np.testing.assert_array_equal(got, want.astype(np.int32))

    check("row_counts_masked", _row_counts)
    check("count_and", _count_and)
    check("bsi_compare_unsigned", _bsi_compare)
    check("masked_matrix_counts", _mmc)

    payload = {
        "status": "ran",
        "platform": dev.platform,
        "device_kind": dev.device_kind,
        "kernels": results,
        "all_ok": all(r["ok"] for r in results.values()),
    }
    json.dump(payload, open(OUT, "w"), indent=1)
    print(json.dumps({"all_ok": payload["all_ok"]}))
    return 0 if payload["all_ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
