#!/usr/bin/env python
"""Measure elastic resize at >= 1B columns (VERDICT round-2 missing #5).

Drives a node JOIN and a node LEAVE through the real resize machinery
(`parallel/resize.py` — plan, instructions, archive transfer, write
block, cleanup; reference cluster.go:1196-1561 + fragment.go:2436-2606
archive path) on a 1,024-shard (1.07B-column) index in an in-process
2->3->2 node cluster, recording wall time, memory, fragments moved,
and post-resize exactness against a deterministic oracle.

Prints one JSON line per phase:
  {"config": "resize-join", "cols": ..., "shards": ..., "wall_s": ...,
   "fragments_moved": ..., "rss_delta_mb": ..., "vm_hwm_mb": ...,
   "exact": true}

Run: PYTHONPATH=/root/repo:$PYTHONPATH python benchmarks/measure_resize.py
(CPU backend is fine — resize is a control-plane + host-IO path; no
device work is being measured.)
"""

from __future__ import annotations

import json
import sys
import tempfile
import time

from pilosa_tpu.axon_guard import guard_dead_relay

guard_dead_relay()

from pilosa_tpu.models.holder import Holder  # noqa: E402
from pilosa_tpu.parallel.cluster import (  # noqa: E402
    Cluster,
    LocalTransport,
    Node,
)
from pilosa_tpu.parallel.node import ClusterNode  # noqa: E402
from pilosa_tpu.parallel.resize import Resizer  # noqa: E402
from pilosa_tpu.shardwidth import SHARD_WIDTH  # noqa: E402

N_SHARDS = 1024          # x 2^20 columns = 1.07B
BITS_PER_ROW = 1_000     # per shard; 2 rows -> ~2M set bits, real archives


def rss() -> tuple[int, int]:
    """(VmRSS, VmHWM) in bytes."""
    cur = hwm = 0
    with open("/proc/self/status") as f:
        for line in f:
            if line.startswith("VmRSS"):
                cur = int(line.split()[1]) * 1024
            elif line.startswith("VmHWM"):
                hwm = int(line.split()[1]) * 1024
    return cur, hwm


def fragment_count(node) -> int:
    total = 0
    for idx in node.holder.indexes.values():
        for f in idx.fields.values():
            for view in f.views.values():
                total += len(view.fragments)
    return total


def main() -> int:
    base = tempfile.mkdtemp(prefix="resize_bench_")
    transport = LocalTransport()
    node_ids = ["node0", "node1"]
    nodes = []
    for nid in node_ids:
        holder = Holder(f"{base}/{nid}")
        cluster = Cluster(nid, nodes=[Node(id=x) for x in node_ids],
                          replica_n=1, transport=transport)
        cluster.set_state("NORMAL")
        nodes.append(ClusterNode(holder, cluster))

    # ---- build the 1.07B-column index, fragments on their owners
    t0 = time.perf_counter()
    for nd in nodes:
        nd.holder.create_index("i").create_field("f")
    oracle_count = {0: N_SHARDS * BITS_PER_ROW, 1: N_SHARDS * BITS_PER_ROW}
    for nd in nodes:
        f = nd.holder.index("i").field("f")
        rows_l, cols_l = [], []
        for shard in range(N_SHARDS):
            owner = nd.cluster.shard_nodes("i", shard)[0].id
            if owner != nd.cluster.local_id:
                continue
            for row in (0, 1):
                # deterministic distinct offsets; row 1 shifted so the
                # intersection is exactly BITS_PER_ROW//2 per shard
                start = 0 if row == 0 else BITS_PER_ROW // 2
                for i in range(BITS_PER_ROW):
                    rows_l.append(row)
                    cols_l.append(shard * SHARD_WIDTH + start + i)
        f.import_bits(rows_l, cols_l)
        f.add_remote_available_shards(set(range(N_SHARDS)))
    build_s = time.perf_counter() - t0
    oracle_inter = N_SHARDS * (BITS_PER_ROW // 2)

    # settle: background compaction + prewarm must not pollute the
    # resize timing
    from pilosa_tpu.runtime import prewarm, snapqueue

    assert prewarm.drain(timeout=600), "prewarm still running"
    assert snapqueue.drain(timeout=600), "compaction still running"

    def check_exact(all_nodes) -> None:
        for nd in all_nodes:
            for row, want in oracle_count.items():
                got = nd.executor.execute("i", f"Count(Row(f={row}))")[0]
                assert got == want, (nd.cluster.local_id, row, got, want)
            got = nd.executor.execute(
                "i", "Count(Intersect(Row(f=0), Row(f=1)))")[0]
            assert got == oracle_inter, (nd.cluster.local_id, got)

    check_exact(nodes)
    out = []

    # ---- JOIN: node2 enters, jump hash re-homes ~1/3 of fragments
    holder2 = Holder(f"{base}/node2")
    cluster2 = Cluster("node2", nodes=[Node(id="node2")], replica_n=1,
                       transport=transport)
    joiner = ClusterNode(holder2, cluster2)
    rss0, _ = rss()
    t0 = time.perf_counter()
    resp = transport.send_message(
        nodes[0].cluster.local_node,
        {"type": "node-join", "node": {"id": "node2", "uri": ""}})
    join_s = time.perf_counter() - t0
    assert resp.get("ok"), resp
    for nd in (*nodes, joiner):
        assert nd.cluster.state == "NORMAL", nd.cluster.local_id
    rss1, hwm1 = rss()
    moved = fragment_count(joiner)
    assert moved > 0, "join moved nothing"
    check_exact([*nodes, joiner])
    out.append({"config": "resize-join", "cols": N_SHARDS * SHARD_WIDTH,
                "shards": N_SHARDS, "wall_s": round(join_s, 1),
                "fragments_moved": moved,
                "rss_delta_mb": round((rss1 - rss0) / 1e6, 1),
                "vm_hwm_mb": round(hwm1 / 1e6, 1),
                "build_s": round(build_s, 1), "exact": True})

    # ---- LEAVE: node2 exits, its fragments re-home to the survivors
    rss0, _ = rss()
    t0 = time.perf_counter()
    leave_res = Resizer(nodes[0]).run(remove_id="node2")
    leave_s = time.perf_counter() - t0
    for nd in nodes:
        assert nd.cluster.state == "NORMAL"
        assert len(nd.cluster.sorted_nodes()) == 2
    rss1, hwm1 = rss()
    check_exact(nodes)
    out.append({"config": "resize-leave", "cols": N_SHARDS * SHARD_WIDTH,
                "shards": N_SHARDS, "wall_s": round(leave_s, 1),
                "fragments_moved": leave_res["transfers"],
                "rss_delta_mb": round((rss1 - rss0) / 1e6, 1),
                "vm_hwm_mb": round(hwm1 / 1e6, 1), "exact": True})

    for rec in out:
        print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
